"""Concurrent serving tier (ISSUE 8, failure path ISSUE 9):
micro-batcher coalescing, bit-identity vs the direct device path,
zero-downtime hot-swap, drain-on-shutdown, mesh placement, percentile
math units — and the failure semantics: request deadlines (expired
requests never coalesced), fail-fast admission control, publish
rollback, retry-then-degrade dispatch, and the close(timeout=) drain
contract."""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.robustness import faults
from lightgbm_tpu.robustness.retry import RetryPolicy
from lightgbm_tpu.serving import (DeadlineExceeded, Generation,
                                  MicroBatcher, ModelServer, Overloaded,
                                  ShutdownError, latency_summary_ms,
                                  percentile)

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


@pytest.fixture(scope="module")
def booster():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(1500, 8)).astype(np.float32).astype(np.float64)
    y = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.normal(size=len(X))
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    return bst, X, y


# ---------------------------------------------------------------------------
# percentile math units
# ---------------------------------------------------------------------------

def test_percentile_nearest_rank():
    xs = list(range(1, 101))          # 1..100
    assert percentile(xs, 50) == 50
    assert percentile(xs, 99) == 99
    assert percentile(xs, 99.9) == 100
    assert percentile(xs, 100) == 100
    assert percentile(xs, 0) == 1
    assert percentile([42.0], 99.9) == 42.0
    assert np.isnan(percentile([], 50))
    # unsorted input must not matter
    assert percentile([5, 1, 3, 2, 4], 50) == 3


def test_percentile_is_an_observed_sample():
    # nearest-rank never interpolates: the result is always a sample
    xs = [1.0, 10.0, 100.0, 1000.0]
    for q in (1, 25, 50, 75, 99, 99.9):
        assert percentile(xs, q) in xs


def test_latency_summary_keys_and_units():
    s = latency_summary_ms([0.001] * 999 + [0.5])
    assert s["n"] == 1000
    assert s["p50_ms"] == 1.0
    assert s["p99_ms"] == 1.0
    assert s["p999_ms"] == 500.0      # the 1000th sample is the tail
    assert s["max_ms"] == 500.0
    assert latency_summary_ms([])["n"] == 0


# ---------------------------------------------------------------------------
# micro-batcher mechanics (spy dispatch, no jax)
# ---------------------------------------------------------------------------

def test_batcher_coalesces_and_respects_max_batch():
    batches = []

    def dispatch(X):
        batches.append(X.shape[0])
        return X[:, 0], Generation(1, 0, 0)

    mb = MicroBatcher(dispatch, max_batch=100, linger_ms=200.0)
    reqs = [mb.submit(np.full((30, 2), i, float)) for i in range(5)]
    vals = [r.result(10) for r in reqs]
    mb.close()
    # 5x30 rows under max_batch=100 -> batches of at most 3 requests
    assert max(batches) <= 100
    assert sum(batches) == 150
    assert len(batches) >= 2          # the 4th request cannot fit in one
    for i, v in enumerate(vals):      # row-aligned split per request
        assert v.shape == (30,) and np.all(v == i)
    assert mb.n_batches == len(batches)


def test_batcher_oversize_request_is_its_own_batch():
    sizes = []

    def dispatch(X):
        sizes.append(X.shape[0])
        return X[:, 0], None

    mb = MicroBatcher(dispatch, max_batch=64, linger_ms=1.0)
    r = mb.submit(np.zeros((300, 2)))
    assert r.result(10).shape == (300,)
    mb.close()
    assert sizes == [300]


def test_batcher_queue_drains_on_shutdown():
    slow = threading.Event()

    def dispatch(X):
        slow.wait(0.01)
        return X[:, 0], None

    mb = MicroBatcher(dispatch, max_batch=8, linger_ms=0.0)
    reqs = [mb.submit(np.zeros((4, 2))) for _ in range(40)]
    mb.close(timeout=30)              # drain everything already accepted
    assert all(r.done() for r in reqs)
    assert all(r.result(0).shape == (4,) for r in reqs)
    with pytest.raises(RuntimeError):
        mb.submit(np.zeros((4, 2)))   # closed


def test_batcher_dispatch_error_fails_the_batch_only():
    calls = []

    def dispatch(X):
        calls.append(X.shape[0])
        if len(calls) == 1:
            raise RuntimeError("boom")
        return X[:, 0], None

    mb = MicroBatcher(dispatch, max_batch=1000, linger_ms=50.0)
    bad = mb.submit(np.zeros((3, 2)))
    with pytest.raises(RuntimeError, match="boom"):
        bad.result(10)
    ok = mb.submit(np.zeros((3, 2)))
    assert ok.result(10).shape == (3,)
    mb.close()
    assert mb.n_errors == 1


def test_batcher_rejects_empty_requests():
    mb = MicroBatcher(lambda X: (X[:, 0], None))
    with pytest.raises(ValueError):
        mb.submit(np.zeros((0, 2)))
    with pytest.raises(ValueError):
        mb.submit(np.zeros(3))
    mb.close()


# ---------------------------------------------------------------------------
# end-to-end server: bit-identity, hot-swap, lifecycle
# ---------------------------------------------------------------------------

def test_microbatched_bit_identical_to_predict_device(booster):
    bst, X, _ = booster
    with bst.serve(linger_ms=100.0, raw_score=True) as srv:
        reqs = [X[i * 83:(i + 1) * 83 + 7 * i] for i in range(5)]
        futs = [srv.submit(r) for r in reqs]
        for r, f in zip(reqs, futs):
            direct = bst.predict(r, device=True, raw_score=True)
            got = f.result(60)
            # bit-identical: same traversal + same f32 accumulation
            # order per row, regardless of how requests coalesced
            assert np.array_equal(got, direct)
        stats = srv.stats()
        assert stats["batches"] < len(reqs)       # coalescing happened
        assert stats["requests"] == len(reqs)


def test_server_converted_output_matches_booster_predict(booster):
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0) as srv:
        got = srv.predict(X[:200], timeout=60)
        assert np.array_equal(got, bst.predict(X[:200], device=True))


@pytest.mark.slow
def test_server_hot_swap_under_load_never_torn(booster):
    bst, X, _ = booster
    probe = X[:64]
    # independent booster so the module fixture stays 5 iterations
    rng = np.random.default_rng(3)
    Xb = rng.normal(size=(800, 6)).astype(np.float32).astype(np.float64)
    yb = Xb[:, 0] - Xb[:, 1]
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbose": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(Xb, label=yb), num_boost_round=3,
                  keep_training_booster=True)
    probe = Xb[:64]
    srv = b.serve(linger_ms=0.5, raw_score=True)
    expected = {srv.generation.version:
                b.predict(probe, device=True, raw_score=True)}
    stop = threading.Event()
    seen = []                          # (version, matched) per response
    errors = []

    def client():
        while not stop.is_set():
            try:
                f = srv.submit(probe)
                v = f.result(60)
                seen.append((f.generation.version, v))
            except Exception as e:     # noqa: BLE001
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    for _ in range(3):                 # publish 3 new generations mid-load
        time.sleep(0.05)
        b.update()
        info = srv.publish()
        expected[info.version] = b.predict(probe, device=True,
                                           raw_score=True)
    time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(60)
    # one deterministic post-publish request: the LAST generation serves
    final = srv.submit(probe)
    final_out = final.result(60)
    srv.close()
    assert not errors, errors
    assert len(seen) > 0
    versions = [v for v, _ in seen]
    # every response is attributable to exactly one published
    # generation and is bit-identical to that generation's model —
    # a torn pack would match neither
    for v, out in seen:
        assert v in expected
        assert np.array_equal(out, expected[v]), \
            f"response from generation {v} matches no published model"
    # generations only move forward (batches serialize on one snapshot)
    assert versions == sorted(versions)
    assert final.generation.version == 4   # all 3 publishes visible
    assert np.array_equal(final_out, expected[4])


@pytest.mark.slow
def test_server_publish_after_rollback_full_repack(booster):
    rng = np.random.default_rng(5)
    Xb = rng.normal(size=(600, 5)).astype(np.float32).astype(np.float64)
    yb = Xb[:, 0] * 2.0
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbose": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(Xb, label=yb), num_boost_round=3,
                  keep_training_booster=True)
    srv = b.serve(linger_ms=0.5, raw_score=True)
    before = srv.predict(Xb[:50], timeout=60)
    b.rollback_one_iter()              # destructive: bumps model gen

    def fobj(preds, _):
        g = np.asarray(preds - yb * 1.5, np.float32)
        return g, np.ones_like(g)

    b.update(fobj=fobj)
    info = srv.publish()
    after = srv.predict(Xb[:50], timeout=60)
    srv.close()
    assert info.num_trees == 3
    assert np.array_equal(after, b.predict(Xb[:50], device=True,
                                           raw_score=True))
    assert not np.array_equal(before, after)


def test_server_loaded_model_raw_route(booster):
    bst, X, _ = booster
    loaded = lgb.Booster(model_str=bst.model_to_string())
    Xf = np.asarray(X[:128], np.float32).astype(np.float64)
    with loaded.serve(linger_ms=1.0, raw_score=True) as srv:
        got = srv.predict(Xf, timeout=60)
        assert np.array_equal(
            got, loaded.predict(Xf, device=True, raw_score=True))


def test_server_knobs_resolve_from_params():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(500, 4)).astype(np.float64)
    y = X[:, 0]
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "tpu_serving_max_batch": 512,
                     "tpu_serving_linger_ms": 7.5},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    with bst.serve() as srv:
        s = srv.stats()
        assert s["max_batch"] == 512
        assert s["linger_ms"] == pytest.approx(7.5)
    with bst.serve(max_batch=64) as srv:     # kwarg overrides param
        assert srv.stats()["max_batch"] == 64


def test_generation_tuple_fields(booster):
    bst, X, _ = booster
    with bst.serve(linger_ms=0.5) as srv:
        g = srv.generation
        assert isinstance(g, Generation)
        assert g.version == 1
        assert g.num_trees == bst.num_trees()
        f = srv.submit(X[:16])
        f.result(60)
        assert f.generation == g
        assert f.latency_sec is not None and f.latency_sec >= 0


# ---------------------------------------------------------------------------
# failure path (ISSUE 9): deadlines, admission control, publish
# rollback, degrade, shutdown drain contract
# ---------------------------------------------------------------------------

def _gated_batcher(max_batch=1000, linger_ms=5.0, **kw):
    """Batcher whose dispatch blocks on an Event — deterministic
    control over when the dispatcher is 'stuck' mid-batch."""
    gate = threading.Event()
    entered = threading.Event()
    dispatched = []

    def dispatch(X):
        entered.set()
        gate.wait(30)
        dispatched.append(X.shape[0])
        return X[:, 0], None

    mb = MicroBatcher(dispatch, max_batch=max_batch, linger_ms=linger_ms,
                      **kw)
    return mb, gate, entered, dispatched


def _drain_to_dispatcher(mb, timeout=5.0):
    """Wait until everything queued has been popped by the dispatcher."""
    end = time.monotonic() + timeout
    while mb.stats()["queued_rows"] and time.monotonic() < end:
        time.sleep(0.005)
    assert mb.stats()["queued_rows"] == 0


def test_batcher_expired_request_never_coalesced():
    # dispatcher is stuck on a blocker batch; a deadline request queued
    # behind it expires and must be dropped BEFORE coalescing — its
    # rows never appear in any dispatched batch
    mb, gate, entered, dispatched = _gated_batcher()
    blocker = mb.submit(np.zeros((7, 2)))
    assert entered.wait(5)
    _drain_to_dispatcher(mb)
    bad = mb.submit(np.zeros((3, 2)), deadline_sec=0.05)
    good = mb.submit(np.zeros((5, 2)))
    time.sleep(0.15)                      # bad expires while queued
    gate.set()
    assert good.result(10).shape == (5,)
    assert blocker.result(10).shape == (7,)
    with pytest.raises(DeadlineExceeded, match="DEADLINE_EXCEEDED"):
        bad.result(10)
    assert 3 not in dispatched, dispatched
    assert mb.counters.get("expired") == 1
    mb.close()


def test_server_expired_request_bit_parity_for_survivors(booster):
    """An expired request must not poison the batch its peers formed:
    the surviving request's response stays bit-identical to the direct
    device path."""
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0, raw_score=True) as srv:
        with faults.inject("slow_dispatch:sec=0.4:n=1"):
            slow = srv.submit(X[:48])     # dispatcher wedges on this
            end = time.monotonic() + 5
            while srv.stats()["queued_rows"] and time.monotonic() < end:
                time.sleep(0.005)
            time.sleep(0.05)  # outlive the linger: queued_rows hits 0 at
            # POP time, while _gather may still coalesce late arrivals
            dead = srv.submit(X[:32], deadline_ms=40.0)
            good = srv.submit(X[64:128])
            got_slow = slow.result(60)
            got_good = good.result(60)
        with pytest.raises(DeadlineExceeded):
            dead.result(60)
        assert np.array_equal(
            got_slow, bst.predict(X[:48], device=True, raw_score=True))
        assert np.array_equal(
            got_good, bst.predict(X[64:128], device=True, raw_score=True))
        assert srv.counters.get("expired") == 1


def test_batcher_overload_fails_fast_with_queue_depth():
    mb, gate, entered, _ = _gated_batcher(max_queue_rows=16)
    blocker = mb.submit(np.zeros((4, 2)))
    assert entered.wait(5)
    _drain_to_dispatcher(mb)
    q1 = mb.submit(np.zeros((8, 2)))
    q2 = mb.submit(np.zeros((8, 2)))      # 16 rows queued: at the bound
    with pytest.raises(Overloaded, match="OVERLOADED.*16 rows"):
        mb.submit(np.zeros((1, 2)))
    assert mb.counters.get("shed") == 1
    gate.set()
    for r in (blocker, q1, q2):           # accepted => still served
        assert r.result(10) is not None
    mb.close()


def test_batcher_oversize_request_admitted_when_idle():
    """A request larger than max_queue_rows must still be servable on
    an idle queue — the bound sheds BACKLOG, it does not define a
    maximum request size."""
    mb = MicroBatcher(lambda X: (X[:, 0], None), max_batch=64,
                      linger_ms=1.0, max_queue_rows=32)
    big = mb.submit(np.zeros((100, 2)))      # 100 > 32, queue empty
    assert big.result(10).shape == (100,)
    assert mb.counters.get("shed") == 0
    mb.close()


def test_batcher_close_not_deadlocked_by_blocked_submitter():
    """close() must honor its timeout even when a submitter is stuck in
    a blocking put on a full queue behind a wedged dispatcher — the
    blocked submitter's request is failed with SHUTDOWN too."""
    mb, gate, entered, _ = _gated_batcher(max_batch=2, linger_ms=0.0,
                                          queue_depth=2)
    first = mb.submit(np.zeros((2, 2)))      # dispatcher takes it, wedges
    assert entered.wait(5)
    _drain_to_dispatcher(mb)
    queued = [mb.submit(np.zeros((2, 2))) for _ in range(2)]  # queue full
    late = []

    def blocked_submit():
        late.append(mb.submit(np.zeros((2, 2))))  # blocks in q.put

    t = threading.Thread(target=blocked_submit, daemon=True)
    t.start()
    time.sleep(0.1)
    t0 = time.perf_counter()
    mb.close(timeout=0.3)
    assert time.perf_counter() - t0 < 10, "close() deadlocked"
    t.join(5)
    assert not t.is_alive(), "submitter still blocked after close"
    for r in [first] + queued + late:
        assert r.done()
        with pytest.raises(ShutdownError):
            r.result(0)
    gate.set()


def test_batcher_late_dispatch_never_double_accounts_shutdown():
    """A dispatch that completes AFTER close() failed its batch with
    SHUTDOWN must not also fulfill/count those requests — and anything
    the resuming dispatcher pops post-abandonment is failed, never
    served (the drain-race closure)."""
    mb, gate, entered, dispatched = _gated_batcher(max_batch=4,
                                                   linger_ms=0.0)
    reqs = [mb.submit(np.zeros((2, 2))) for _ in range(4)]
    assert entered.wait(5)                # batch 1 wedged mid-dispatch
    mb.close(timeout=0.2)
    assert all(r.done() for r in reqs)
    assert mb.counters.get("shutdown_failed") == 4
    gate.set()                            # wedged dispatch completes now
    mb._thread.join(10)
    assert not mb._thread.is_alive()
    # the late completion neither re-served nor re-counted anything
    assert mb.n_requests == 0
    assert mb.latency.total == 0
    for r in reqs:
        with pytest.raises(ShutdownError):
            r.result(0)


def test_predict_timeout_slot_reclaimed(booster):
    """predict(timeout=) rides the deadline machinery: after the
    timeout the dispatcher DROPS the request (slot reclaimed), it is
    never served into the void."""
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0, raw_score=True) as srv:
        with faults.inject("slow_dispatch:sec=0.5:n=1"):
            slow = srv.submit(X[:32])     # wedge the dispatcher
            end = time.monotonic() + 5
            while srv.stats()["queued_rows"] and time.monotonic() < end:
                time.sleep(0.005)
            time.sleep(0.05)              # outlive the linger window
            with pytest.raises(TimeoutError):
                srv.predict(X[:16], timeout=0.05)
            slow.result(60)
        end = time.monotonic() + 5        # the expired predict's drop
        while srv.counters.get("expired") < 1 and time.monotonic() < end:
            time.sleep(0.005)
        assert srv.counters.get("expired") == 1
        # the abandoned request's rows never reached a dispatch
        assert srv.stats()["rows"] == 32


def test_publish_fail_rolls_back_generation_monotonic():
    rng = np.random.default_rng(17)
    Xb = rng.normal(size=(500, 5)).astype(np.float32).astype(np.float64)
    yb = Xb[:, 0] * 2.0
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbose": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(Xb, label=yb), num_boost_round=3,
                  keep_training_booster=True)
    srv = b.serve(linger_ms=1.0, raw_score=True)
    old = srv.predict(Xb[:40], timeout=60)
    v0 = srv.generation.version
    b.update()
    with faults.inject("publish_fail"):
        with pytest.raises(faults.FaultInjected):
            srv.publish()
    # rollback: version untouched, OLD generation still serving
    assert srv.generation.version == v0
    assert np.array_equal(srv.predict(Xb[:40], timeout=60), old)
    assert srv.counters.get("publish_failures") == 1
    # the pack-append site (consult #2, after=1) rolls back too
    with faults.inject("publish_fail:after=1:n=1"):
        with pytest.raises(faults.FaultInjected):
            srv.publish()
    assert srv.generation.version == v0
    # next publish succeeds gaplessly and serves the new trees
    info = srv.publish()
    assert info.version == v0 + 1
    assert np.array_equal(
        srv.predict(Xb[:40], timeout=60),
        b.predict(Xb[:40], device=True, raw_score=True))
    srv.close()


def test_degraded_route_bit_identical_to_host_walk(booster):
    bst, X, _ = booster
    srv = bst.serve(linger_ms=1.0, raw_score=True, probe_interval_s=0.05)
    try:
        direct = bst.predict(X[:80], device=True, raw_score=True)
        srv.degrade("test: forced")
        got = srv.predict(X[:80], timeout=60)
        # degraded = the HOST walk, bit-identical to Booster.predict
        assert np.array_equal(got, bst.predict(X[:80], raw_score=True))
        assert srv.stats()["degraded"]
        assert srv.counters.get("degraded_batches") >= 1
        # background probe un-degrades (device is healthy here)
        end = time.monotonic() + 10
        while srv.stats()["degraded"] and time.monotonic() < end:
            time.sleep(0.02)
        assert not srv.stats()["degraded"]
        assert srv.counters.get("recoveries") == 1
        assert np.array_equal(srv.predict(X[:80], timeout=60), direct)
    finally:
        srv.close()


def test_retry_exhaustion_degrades_and_still_answers(booster):
    bst, X, _ = booster
    srv = bst.serve(linger_ms=1.0, raw_score=True, probe_interval_s=0.0,
                    retry_policy=RetryPolicy(max_attempts=2,
                                             base_delay=0.001,
                                             max_delay=0.01,
                                             deadline=2.0))
    try:
        with faults.inject("dispatch_error:p=1:n=2"):
            got = srv.predict(X[:64], timeout=60)
        # the wedged batch is still ANSWERED — via the host walk
        assert np.array_equal(got, bst.predict(X[:64], raw_score=True))
        s = srv.stats()
        assert s["degraded"] and "exhausted" in s["degraded_reason"]
        assert srv.counters.get("dispatch_failures") == 1
        assert srv.counters.get("dispatch_retries") == 1
        # probe_interval_s=0: degradation is sticky (no probe thread)
        assert srv.counters.get("recoveries") == 0
    finally:
        srv.close()


def test_transient_dispatch_fault_retried_bit_identical(booster):
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0, raw_score=True) as srv:
        with faults.inject("dispatch_error"):
            got = srv.predict(X[:64], timeout=60)
        assert np.array_equal(
            got, bst.predict(X[:64], device=True, raw_score=True))
        assert srv.counters.get("dispatch_retries") == 1
        assert not srv.stats()["degraded"]


def test_nontransient_dispatch_error_fails_batch_not_degrades():
    calls = []

    def dispatch(X):
        calls.append(X.shape[0])
        raise ValueError("a code bug, not a flaky device")

    mb = MicroBatcher(dispatch, max_batch=100, linger_ms=1.0)
    r = mb.submit(np.zeros((3, 2)))
    with pytest.raises(ValueError, match="code bug"):
        r.result(10)
    mb.close()
    assert mb.n_errors == 1


def test_batcher_close_timeout_fails_pending_with_shutdown():
    """ISSUE 9 satellite: a drain past the timeout must FAIL every
    still-pending future (SHUTDOWN), never abandon a blocked client."""
    mb, gate, entered, _ = _gated_batcher(max_batch=4, linger_ms=0.0)
    reqs = [mb.submit(np.zeros((2, 2))) for _ in range(6)]
    assert entered.wait(5)                # dispatcher stuck mid-batch
    t0 = time.perf_counter()
    mb.close(timeout=0.3)
    assert time.perf_counter() - t0 < 10
    assert all(r.done() for r in reqs), "a client would block forever"
    for r in reqs:
        with pytest.raises(ShutdownError, match="SHUTDOWN"):
            r.result(0)
    assert mb.counters.get("shutdown_failed") == len(reqs)
    gate.set()                            # unwedge the daemon thread


def test_server_deadline_knob_resolves_from_params():
    rng = np.random.default_rng(23)
    X = rng.normal(size=(400, 4)).astype(np.float64)
    y = X[:, 0]
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5,
                     "tpu_serving_deadline_ms": 1234.0,
                     "tpu_serving_max_queue_rows": 4096},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    with bst.serve() as srv:
        s = srv.stats()
        assert s["deadline_ms"] == pytest.approx(1234.0)
        assert s["max_queue_rows"] == 4096
    with bst.serve(deadline_ms=0.0, max_queue_rows=0) as srv:
        assert srv.stats()["deadline_ms"] == 0.0
        assert srv.stats()["max_queue_rows"] == 0


# ---------------------------------------------------------------------------
# memory-pressure survival (ISSUE 17): OOM-classified adaptive dispatch
# ---------------------------------------------------------------------------

def test_oom_dispatch_bisects_bit_identical_not_degraded(booster):
    """A size-induced OOM on the coalesced batch bisects and retries —
    halves are already-warm bucket shapes, results bit-identical to the
    full-batch device dispatch, and the server is NOT degraded (the
    whole-server host route is for retry exhaustion, not for a batch
    that was merely too big)."""
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0, raw_score=True) as srv:
        with faults.inject("oom:n=1"):
            got = srv.predict(X[:600], timeout=120)
        st = srv.stats()
        assert st["oom_bisects"] >= 1
        assert not st["degraded"]
        assert srv.counters.get("dispatch_retries") == 0  # never retried
    assert np.array_equal(
        got, bst.predict(X[:600], device=True, raw_score=True))


def test_oom_bisection_floor_degrades_only_failing_rows(booster):
    """oom:n=3 fails the 600-row batch, its left 300 half, and the left
    150 quarter (under the 256-row floor -> host walk); every OTHER row
    stays on the device. Per-request blast radius, not per-server."""
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0, raw_score=True) as srv:
        with faults.inject("oom:p=1:n=3"):
            got = srv.predict(X[:600], timeout=120)
        st = srv.stats()
        assert st["oom_bisects"] == 2      # 600 and 300 bisected
        assert not st["degraded"]
    ref_dev = bst.predict(X[:600], device=True, raw_score=True)
    ref_host = bst.predict(X[:600], device=False, raw_score=True)
    np.testing.assert_allclose(got[:150], ref_host[:150],
                               rtol=1e-12, atol=1e-12)
    assert np.array_equal(got[150:], ref_dev[150:])


def test_oom_floor_everywhere_host_walks_without_degrading(booster):
    """Persistent OOM (every attempt) floors every slice to the host
    walk — the batch is still answered and the server still is NOT
    degraded: the background probe has nothing to un-degrade, and the
    next OOM-free batch runs on the device again."""
    bst, X, _ = booster
    with bst.serve(linger_ms=1.0, raw_score=True) as srv:
        with faults.inject("oom:p=1:n=1000000"):
            got = srv.predict(X[:100], timeout=120)
        assert not srv.stats()["degraded"]
        clean = srv.predict(X[:100], timeout=120)
    np.testing.assert_allclose(
        got, bst.predict(X[:100], device=False, raw_score=True),
        rtol=1e-12, atol=1e-12)
    assert np.array_equal(
        clean, bst.predict(X[:100], device=True, raw_score=True))


@pytest.mark.slow
def test_server_mesh_two_virtual_devices_subprocess(booster):
    """Mesh replication needs >1 device, which needs XLA_FLAGS before
    jax import — so the 2-virtual-device parity proof runs in a
    subprocess (same pattern as the multiprocess suite)."""
    code = r"""
import numpy as np
import jax
import lightgbm_tpu as lgb
assert len(jax.devices()) == 2, jax.devices()
rng = np.random.default_rng(0)
X = rng.normal(size=(600, 6)).astype(np.float32).astype(np.float64)
y = X[:, 0] + X[:, 1]
bst = lgb.train({"objective": "regression", "num_leaves": 15,
                 "verbose": -1, "min_data_in_leaf": 5},
                lgb.Dataset(X, label=y), num_boost_round=3)
srv = bst.serve(linger_ms=20.0, raw_score=True, num_devices=2)
assert srv.stats()["mesh_devices"] == 2
futs = [srv.submit(X[i * 100:(i + 1) * 100]) for i in range(4)]
for i, f in enumerate(futs):
    direct = bst.predict(X[i * 100:(i + 1) * 100], device=True,
                         raw_score=True)
    assert np.array_equal(f.result(120), direct)
srv.close()
print("MESH_OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=2").strip()
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "MESH_OK" in out.stdout
