"""Crash-safety fuzz of the native model-text parser.

The serving library parses untrusted model files; mutated/truncated
inputs must produce rc=-1 (with an error message) or a valid load —
never a crash. Runs in a SUBPROCESS so a segfault fails the test
instead of killing the pytest process.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.native import get_lib

pytestmark = pytest.mark.skipif(get_lib() is None,
                                reason="no native toolchain")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ONE copy of the native fuzz body: scripts/_native_fuzz_driver.py is
# shared with the opt-in ASan/UBSan gate (scripts/native_sanitize.sh),
# which runs the same driver against the sanitizer build.
_FUZZ_DRIVER = os.path.join(REPO, "scripts", "_native_fuzz_driver.py")


def test_model_parser_fuzz(rng, tmp_path):
    X = rng.normal(size=(400, 6))
    X[:, 2] = rng.integers(0, 5, size=400)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[2]),
                    num_boost_round=4)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)

    so_path = os.path.join(REPO, "lightgbm_tpu", "native", "_build",
                           "lgbm_native.so")
    out = subprocess.run([sys.executable, _FUZZ_DRIVER, so_path, path],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (
        f"parser fuzz crashed (rc={out.returncode}):\n"
        f"{out.stderr[-1500:]}")
    assert "FUZZ-OK" in out.stdout


_PY_FUZZ_CODE = r"""
import random, resource, sys
resource.setrlimit(resource.RLIMIT_AS, (4 << 30, 4 << 30))
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
import lightgbm_tpu as lgb

model = open(sys.argv[1]).read()
rng = random.Random(99)

def try_load(s):
    try:
        b = lgb.Booster(model_str=s)
        b.predict([[0.0] * 8])
    except MemoryError:
        pass      # rlimit tripped on a pathological size: acceptable
    except Exception:
        pass      # graceful rejection

for frac in (0.2, 0.5, 0.8, 0.95):
    try_load(model[: int(len(model) * frac)])
lines = model.split("\n")
for _ in range(40):
    mutated = list(lines)
    op = rng.randrange(3)
    i = rng.randrange(len(mutated))
    if op == 0:
        del mutated[i]
    elif op == 1:
        mutated.insert(i, mutated[i])
    else:
        mutated[i] = mutated[i].replace("1", "987654321")
    try_load("\n".join(mutated))
print("PY-FUZZ-OK")
"""


def test_python_model_loader_fuzz(rng, tmp_path):
    """The Python model loader must reject corrupt model text with an
    exception (never crash/hang/absurd allocation past the rlimit)."""
    X = rng.normal(size=(300, 8))
    y = X[:, 0] * 2 + rng.normal(scale=0.1, size=300)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    script = tmp_path / "pyfuzz.py"
    script.write_text(_PY_FUZZ_CODE)
    out = subprocess.run([sys.executable, str(script), path, "-", REPO],
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "PY-FUZZ-OK" in out.stdout


_FILE_FUZZ_CODE = r"""
import random, resource, sys
resource.setrlimit(resource.RLIMIT_AS, (4 << 30, 4 << 30))
sys.path.insert(0, sys.argv[2])
import jax
jax.config.update("jax_platforms", "cpu")
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.file_loader import load_svm_or_csv

rng = random.Random(7)
base_csv = "\n".join(
    ",".join(f"{rng.random():.4g}" for _ in range(5)) for _ in range(50))
base_svm = "\n".join(
    f"{i % 2} " + " ".join(f"{j}:{rng.random():.4g}"
                           for j in sorted(rng.sample(range(20), 3)))
    for i in range(50))

import os
tmp = sys.argv[1]

def try_parse(text, suffix):
    p = os.path.join(tmp, f"f{suffix}")
    with open(p, "w") as fh:
        fh.write(text)
    try:
        load_svm_or_csv(p, Config({"min_data_in_leaf": 1}))
    except SystemExit:
        pass   # log.fatal path: graceful
    except Exception:
        pass

cases = 0
for base in (base_csv, base_svm):
    for _ in range(30):
        b = list(base)
        for _ in range(8):
            b[rng.randrange(len(b))] = chr(rng.randrange(1, 127))
        try_parse("".join(b), cases)
        cases += 1
    lines = base.split("\n")
    for _ in range(20):
        m = list(lines)
        i = rng.randrange(len(m))
        m[i] = m[i] * 50 if rng.random() < 0.5 else m[i][:rng.randrange(
            len(m[i]) + 1)]
        try_parse("\n".join(m), cases)
        cases += 1
# pathological one-liners
for text in (":", "1:", "a:b c:d", ",,,,,", "\x00\x01\x02", "9" * 10000,
             "1 99999999999999:1"):
    try_parse(text, cases)
    cases += 1

# the two_round streaming loader drives the NATIVE chunk parsers
from lightgbm_tpu.io.stream_loader import load_binned_two_round

def try_stream(text, suffix):
    p = os.path.join(tmp, f"s{suffix}")
    with open(p, "w") as fh:
        fh.write(text)
    try:
        load_binned_two_round(p, Config({"two_round": True,
                                         "min_data_in_bin": 1,
                                         "min_data_in_leaf": 1}),
                              chunk_bytes=256)
    except SystemExit:
        pass
    except Exception:
        pass

for base in (base_csv, base_svm):
    for _ in range(10):
        b = list(base)
        for _ in range(8):
            b[rng.randrange(len(b))] = chr(rng.randrange(1, 127))
        try_stream("".join(b), cases)
        cases += 1
print("FILE-FUZZ-OK", cases)
"""


def test_file_parser_fuzz(tmp_path):
    """CSV/TSV/LibSVM ingestion (incl. the native chunk parsers) must
    reject or survive corrupt files — no crash, no runaway allocation."""
    script = tmp_path / "filefuzz.py"
    script.write_text(_FILE_FUZZ_CODE)
    out = subprocess.run([sys.executable, str(script), str(tmp_path),
                          REPO],
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    assert "FILE-FUZZ-OK" in out.stdout
