"""External collective injection (≡ LGBM_NetworkInitWithFunctions).

The reference lets an embedding host supply reduce-scatter/allgather
function pointers instead of its socket/MPI linkers
(ref: include/LightGBM/c_api.h:1674, src/network/network.cpp:49-62);
SynapseML is the canonical consumer. Here the analogue is
`lightgbm_tpu.distributed.inject_collectives`: user callables carry
every cross-worker reduction of the serial grower via io_callback.

The test builds a REAL 2-worker world inside one process: two threads,
each training a Booster on half the rows (shared bin boundaries via
``reference=``), with a barrier-based deterministic allreduce. Under
use_quantized_grad with deterministic rounding the histograms are exact
int32 sums, so the 2-worker model must equal centralized training
bit-for-bit — the same guarantee the data-parallel mesh path proves in
test_quantized.py.
"""
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.distributed import (clear_collectives,
                                      inject_collectives)

PARAMS = {
    "objective": "regression",
    "num_leaves": 15,
    "learning_rate": 0.2,
    "min_data_in_leaf": 5,
    "use_quantized_grad": True,
    "stochastic_rounding": False,
    "verbosity": -1,
}
ROUNDS = 6


class ThreadAllreduce:
    """Deterministic allreduce over threads: every rank deposits, all
    wait, every rank computes the same fixed-order sum/max."""

    def __init__(self, world):
        self.world = world
        self.barrier = threading.Barrier(world)
        self.bufs = [None] * world
        self.calls = 0

    def _exchange(self, rank, arr, op):
        self.bufs[rank] = np.asarray(arr).copy()
        self.barrier.wait()
        out = self.bufs[0].astype(np.float64) if op == "sum" \
            else self.bufs[0]
        for b in self.bufs[1:]:
            out = out + b if op == "sum" else np.maximum(out, b)
        self.calls += 1
        self.barrier.wait()   # all read before the next deposit
        return out.astype(arr.dtype)

    def make(self, rank):
        return (lambda a: self._exchange(rank, a, "sum"),
                lambda a: self._exchange(rank, a, "max"))


@pytest.mark.slow
def test_injected_two_worker_matches_centralized(rng):
    n, f = 600, 6
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] * 2 - X[:, 1] * X[:, 2] +
         0.05 * rng.normal(size=n)).astype(np.float32)

    # centralized baseline (no injection)
    clear_collectives()
    full = lgb.Dataset(X, label=y)
    bst_c = lgb.train(dict(PARAMS), full, num_boost_round=ROUNDS)
    pred_c = bst_c.predict(X)

    # two workers: shared bin boundaries via reference=, half rows each
    allred = ThreadAllreduce(2)
    halves = [(X[: n // 2], y[: n // 2]), (X[n // 2:], y[n // 2:])]
    boosters = [None, None]
    # sequential setup (each Booster snapshots its own rank), then
    # concurrent training (reductions meet at the barrier)
    for rank in range(2):
        rsum, rmax = allred.make(rank)
        inject_collectives(rsum, reduce_max=rmax, rank=rank,
                           num_machines=2)
        ds = lgb.Dataset(halves[rank][0], label=halves[rank][1],
                         reference=full)
        boosters[rank] = lgb.Booster(dict(PARAMS), ds)
    clear_collectives()

    errs = []

    def run(rank):
        try:
            for _ in range(ROUNDS):
                boosters[rank].update()
        except Exception as e:          # pragma: no cover
            errs.append((rank, e))
            try:
                allred.barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not errs, errs
    assert allred.calls > 0, "injected collectives never invoked"

    # both workers hold the identical global model...
    m0 = boosters[0].model_to_string()
    m1 = boosters[1].model_to_string()
    assert m0 == m1
    # ...equal to centralized training (exact int32 histogram algebra)
    pred_0 = boosters[0].predict(X)
    np.testing.assert_allclose(pred_0, pred_c, rtol=1e-6, atol=1e-7)


def test_inject_validation():
    with pytest.raises(TypeError):
        inject_collectives("not callable")
    clear_collectives()
