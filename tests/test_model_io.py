"""Model text format round-trip tests (ref: the reference's model-file
round-trip tier — tests/python_package_test/test_basic.py save/load)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(rng, n=1500, f=8):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + rng.normal(scale=0.1, size=n)
    return X, y


def test_model_string_roundtrip(rng, tmp_path):
    X, y = _data(rng)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=12)
    s = bst.model_to_string()
    assert s.startswith("tree\nversion=v4\n")
    assert "end of trees" in s
    assert "feature_importances:" in s
    assert "parameters:" in s

    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-12, atol=1e-12)

    path = tmp_path / "model.txt"
    bst.save_model(path)
    bst3 = lgb.Booster(model_file=str(path))
    np.testing.assert_allclose(bst.predict(X), bst3.predict(X),
                               rtol=1e-12, atol=1e-12)


def test_binary_model_roundtrip(rng):
    X, y = _data(rng)
    yb = (y > np.median(y)).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=yb),
                    num_boost_round=10)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-10)
    # transformed output still sigmoid
    p = bst2.predict(X)
    assert (p >= 0).all() and (p <= 1).all()


def test_multiclass_model_roundtrip(rng):
    X, _ = _data(rng, n=900)
    y = rng.integers(0, 3, size=900).astype(np.float64)
    X[:, 0] += y * 2  # separable signal
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-10)


def test_continue_training_from_file(rng, tmp_path):
    X, y = _data(rng)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    path = tmp_path / "m.txt"
    bst.save_model(path)
    bst2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8,
                     init_model=str(path))
    assert bst2.num_trees() == 16
    mse1 = float(np.mean((bst.predict(X) - y) ** 2))
    mse2 = float(np.mean((bst2.predict(X) - y) ** 2))
    assert mse2 < mse1


def test_dump_model(rng):
    X, y = _data(rng, n=800)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=3)
    d = bst.dump_model()
    assert d["version"] == "v4"
    assert len(d["tree_info"]) == 3
    ts = d["tree_info"][0]["tree_structure"]
    assert "split_feature" in ts and "left_child" in ts


def test_num_iteration_predict(rng):
    X, y = _data(rng)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=20)
    p5 = bst.predict(X, num_iteration=5)
    p20 = bst.predict(X)
    assert not np.allclose(p5, p20)
    mse5 = np.mean((p5 - y) ** 2)
    mse20 = np.mean((p20 - y) ** 2)
    assert mse20 < mse5


def test_pred_leaf(rng):
    X, y = _data(rng, n=600)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    leaves = bst.predict(X, pred_leaf=True)
    assert leaves.shape == (600, 5)
    assert leaves.max() < 8
    assert leaves.min() >= 0


def test_pred_contrib_sums_to_prediction(rng):
    X, y = _data(rng, n=300)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    contrib = bst.predict(X, pred_contrib=True)
    assert contrib.shape == (300, X.shape[1] + 1)
    raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6,
                               atol=1e-6)


def test_feature_importance(rng):
    X, y = _data(rng)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    imp_split = bst.feature_importance("split")
    imp_gain = bst.feature_importance("gain")
    assert imp_split.dtype == np.int64
    assert imp_split.sum() > 0
    # features 0 and 1 carry the signal
    assert imp_gain[0] > imp_gain[3]
