"""Fault-tolerant training gang (ISSUE 10): the fast tier-1 family.

Promotes the old ``@slow`` kill-one-rank-relaunch-resume subprocess test
to unit coverage that needs no gang:

- gang manifests: round-trip, CRC corruption fallback, torn-commit
  skipping (manifest without its checkpoint / iteration disagreement);
- resume refusal: world-size mismatch, shard-digest mismatch (with the
  per-rank diagnosis), checkpoints-without-manifest;
- resume-iteration agreement: resume anchors at the newest COMMITTED
  iteration, not the newest raw checkpoint;
- collective liveness: a blocked collective raises CollectiveTimeout
  within the deadline and is NOT retried in-process;
- fault grammar: ``rank_kill`` (rank filter + after/fire accounting)
  and ``collective_delay``;
- GangSupervisor: rank death SIGTERMs the survivors with a per-rank
  diagnosis; a silent rank is classified; ``launch_local``'s blunt
  timeout carries forensics.

The end-to-end chaos round-trip (rank kill → auto-relaunch →
bit-identical model) is gated by scripts/gang_chaos_smoke.py in
check.sh.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from lightgbm_tpu import distributed
from lightgbm_tpu.distributed import (CollectiveTimeout, launch_local,
                                      retried_collective,
                                      set_collective_timeout)
from lightgbm_tpu.io.dataset_core import ShardInfo
from lightgbm_tpu.robustness import checkpoint as ck
from lightgbm_tpu.robustness import faults, gang
from lightgbm_tpu.robustness.gang import (GangError, GangSupervisor,
                                          GangTimeout,
                                          latest_valid_manifest,
                                          validate_and_select_resume,
                                          write_manifest)
from lightgbm_tpu.robustness.heartbeat import StallPolicy, rank_path
from lightgbm_tpu.utils.log import LightGBMError


def _shard(world=2, counts=(10, 11), digests=(0xDEADBEEF, 0x12345678),
           rank=0):
    return ShardInfo(rank=rank, world=world,
                     row_counts=np.asarray(counts, np.int64),
                     digests=tuple(digests) if digests else None)


def _commit(d, iteration, shard, model=None):
    """One committed checkpoint+manifest pair."""
    path = ck.write_checkpoint(
        str(d), {"iteration": iteration,
                 "model": model or f"MODEL{iteration}"})
    write_manifest(str(d), iteration, os.path.basename(path), shard)
    return path


# ---------------------------------------------------------------------------
# Gang manifests
# ---------------------------------------------------------------------------

def test_manifest_round_trip(tmp_path):
    shard = _shard()
    _commit(tmp_path, 4, shard)
    man, ckpt_path = latest_valid_manifest(str(tmp_path))
    assert man["iteration"] == 4
    assert man["world"] == 2
    assert man["row_counts"] == [10, 11]
    assert man["digests"] == ["deadbeef", "12345678"]
    assert man["checkpoint"] == "ckpt_000000004.lgbmckpt"
    assert ck.read_checkpoint(ckpt_path)["model"] == "MODEL4"


def test_manifest_requires_digests(tmp_path):
    with pytest.raises(ValueError, match="digests"):
        write_manifest(str(tmp_path), 1, "ckpt_000000001.lgbmckpt",
                       _shard(digests=None))


def test_manifest_crc_corruption_falls_back(tmp_path):
    shard = _shard()
    _commit(tmp_path, 2, shard)
    _commit(tmp_path, 4, shard)
    newest = tmp_path / gang.manifest_name(4)
    blob = bytearray(newest.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    newest.write_bytes(bytes(blob))
    man, _ = latest_valid_manifest(str(tmp_path))
    assert man["iteration"] == 2


def test_torn_commit_manifest_without_checkpoint_skipped(tmp_path):
    shard = _shard()
    _commit(tmp_path, 2, shard)
    # a manifest whose checkpoint never landed (or was corrupted) is an
    # uncommitted torn write — skipped, never resumed from
    write_manifest(str(tmp_path), 5, ck.checkpoint_name(5), shard)
    man, _ = latest_valid_manifest(str(tmp_path))
    assert man["iteration"] == 2
    # corrupt (not just missing) checkpoint is equally torn
    p6 = ck.write_checkpoint(str(tmp_path), {"iteration": 6,
                                             "model": "M6"})
    write_manifest(str(tmp_path), 6, os.path.basename(p6), shard)
    blob = bytearray(open(p6, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(p6, "wb").write(bytes(blob))
    man, _ = latest_valid_manifest(str(tmp_path))
    assert man["iteration"] == 2


def test_torn_commit_iteration_disagreement_skipped(tmp_path):
    shard = _shard()
    _commit(tmp_path, 2, shard)
    # manifest 7 pointing at checkpoint holding iteration 3: torn
    p3 = ck.write_checkpoint(str(tmp_path), {"iteration": 3,
                                             "model": "M3"})
    write_manifest(str(tmp_path), 7, os.path.basename(p3), shard)
    man, _ = latest_valid_manifest(str(tmp_path))
    assert man["iteration"] == 2


def test_prune_manifests_keeps_newest(tmp_path):
    shard = _shard()
    for it in (1, 2, 3, 4):
        _commit(tmp_path, it, shard)
    removed = gang.prune_manifests(str(tmp_path), keep_last=2)
    assert removed == 2
    left = sorted(p.name for p in tmp_path.iterdir()
                  if p.name.endswith(".manifest"))
    assert left == [gang.manifest_name(3), gang.manifest_name(4)]


# ---------------------------------------------------------------------------
# Resume validation / selection
# ---------------------------------------------------------------------------

def test_fresh_start_with_no_checkpoints(tmp_path):
    assert validate_and_select_resume(str(tmp_path), _shard(),
                                      None) is None


def test_checkpoints_without_manifest_refused(tmp_path):
    ck.write_checkpoint(str(tmp_path), {"iteration": 3, "model": "M"})
    with pytest.raises(LightGBMError, match="no valid committed gang"):
        validate_and_select_resume(str(tmp_path), _shard(), None)


def test_world_size_mismatch_refused(tmp_path):
    _commit(tmp_path, 4, _shard())
    sel = ck.latest_valid_checkpoint(str(tmp_path))[1]
    with pytest.raises(LightGBMError, match="mixed-world"):
        validate_and_select_resume(
            str(tmp_path),
            _shard(world=3, counts=(7, 7, 7), digests=(1, 2, 3)), sel)


def test_shard_digest_mismatch_refused_with_rank_diagnosis(tmp_path):
    _commit(tmp_path, 4, _shard())
    sel = ck.latest_valid_checkpoint(str(tmp_path))[1]
    with pytest.raises(LightGBMError) as ei:
        validate_and_select_resume(
            str(tmp_path), _shard(digests=(0xDEADBEEF, 0x1)), sel)
    msg = str(ei.value)
    assert "DIFFERENT sharding" in msg
    assert "rank 1" in msg and "rank 0" not in msg


def test_row_count_mismatch_refused(tmp_path):
    _commit(tmp_path, 4, _shard())
    sel = ck.latest_valid_checkpoint(str(tmp_path))[1]
    with pytest.raises(LightGBMError, match="rank 0"):
        validate_and_select_resume(str(tmp_path),
                                   _shard(counts=(9, 11)), sel)


def test_resume_iteration_agreement_anchors_at_manifest(tmp_path):
    """The newest RAW checkpoint may be an uncommitted torn write;
    resume must anchor at the newest COMMITTED iteration so every rank
    and every relaunch agree."""
    shard = _shard()
    _commit(tmp_path, 4, shard)
    ck.write_checkpoint(str(tmp_path), {"iteration": 6, "model": "M6"})
    sel = ck.latest_valid_checkpoint(str(tmp_path))[1]
    assert sel["iteration"] == 6
    state = validate_and_select_resume(str(tmp_path), shard, sel)
    assert state["iteration"] == 4
    assert state["model"] == "MODEL4"


# ---------------------------------------------------------------------------
# Collective liveness
# ---------------------------------------------------------------------------

def test_collective_timeout_raises_within_deadline_not_retried():
    calls = []

    def blocked(a):
        calls.append(1)
        time.sleep(10)
        return a

    set_collective_timeout(0.2)
    try:
        t0 = time.monotonic()
        with pytest.raises(CollectiveTimeout) as ei:
            retried_collective(blocked, np.zeros(2), what="t")
        took = time.monotonic() - t0
    finally:
        set_collective_timeout(0)
    assert took < 3.0, f"deadline took {took:.1f}s"
    # DEADLINE_EXCEEDED marker for OUTER (gang-level) classification...
    assert "DEADLINE_EXCEEDED" in str(ei.value)
    # ...but never retried in-process: re-driving a collective round
    # while the previous one is still blocked would desync the gang
    assert len(calls) == 1


def test_collective_timeout_passthrough_and_errors():
    set_collective_timeout(5.0)
    try:
        out = retried_collective(lambda a: a + 1, np.zeros(3))
        np.testing.assert_array_equal(out, np.ones(3))
        # a non-transient error inside the deadline thread propagates
        # as itself (a code bug must never burn the retry budget)
        with pytest.raises(ZeroDivisionError):
            retried_collective(lambda a: 1 / 0, np.zeros(1))
    finally:
        set_collective_timeout(0)


def test_collective_timeout_resolution(monkeypatch):
    set_collective_timeout(0)
    monkeypatch.delenv(distributed.ENV_COLLECTIVE_TIMEOUT,
                       raising=False)
    assert distributed.collective_timeout() == \
        distributed.DEFAULT_COLLECTIVE_TIMEOUT
    monkeypatch.setenv(distributed.ENV_COLLECTIVE_TIMEOUT, "42.5")
    assert distributed.collective_timeout() == 42.5
    set_collective_timeout(7.0)          # explicit pin wins over env
    try:
        assert distributed.collective_timeout() == 7.0
    finally:
        set_collective_timeout(0)


# ---------------------------------------------------------------------------
# Fault grammar: rank_kill / collective_delay
# ---------------------------------------------------------------------------

def test_rank_kill_grammar_and_accounting():
    exits = []
    with faults.inject("rank_kill:rank=1:after=2") as plan:
        f = plan.faults["rank_kill"]
        assert (f.rank, f.after, f.n) == (1, 2, 1)
        for _ in range(5):                       # wrong rank: filtered
            faults.maybe_kill_rank(0, _exit=exits.append)
        assert exits == [] and f.calls == 0
        faults.maybe_kill_rank(1, _exit=exits.append)
        faults.maybe_kill_rank(1, _exit=exits.append)
        assert exits == []                       # after=2 skips two
        faults.maybe_kill_rank(1, _exit=exits.append)
        assert exits == [faults.EXIT_RANK_KILLED]
        faults.maybe_kill_rank(1, _exit=exits.append)
        assert len(exits) == 1                   # n defaults to 1
    # bare rank_kill fires for ANY rank
    exits = []
    with faults.inject("rank_kill"):
        faults.maybe_kill_rank(3, _exit=exits.append)
    assert exits == [faults.EXIT_RANK_KILLED]


def test_collective_delay_fires_inside_deadline():
    slept = []
    with faults.inject("collective_delay:sec=1.5"):
        assert faults.maybe_delay("collective_delay",
                                  sleep=slept.append) == 1.5
    assert slept == [1.5]
    # and through retried_collective, a short delay under a generous
    # deadline completes normally (delay != failure)
    set_collective_timeout(10.0)
    try:
        with faults.inject("collective_delay:sec=0.05"):
            out = retried_collective(lambda a: a + 2, np.zeros(2))
        np.testing.assert_array_equal(out, np.full(2, 2.0))
    finally:
        set_collective_timeout(0)


def test_known_sites_cover_gang_grammar():
    assert "rank_kill" in faults.KNOWN_SITES
    assert "collective_delay" in faults.KNOWN_SITES


# ---------------------------------------------------------------------------
# GangSupervisor (tiny real subprocesses, no jax in the children)
# ---------------------------------------------------------------------------

_POLICY = StallPolicy(stall_sec={}, default_stall=60.0, silent_sec=60.0,
                      startup_grace=60.0)


def _sleeper(seconds=60):
    return subprocess.Popen([sys.executable, "-c",
                             f"import time; time.sleep({seconds})"],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def test_gang_supervisor_rank_death_terminates_survivors(tmp_path):
    hb = str(tmp_path / "g.hb")
    procs = [_sleeper(),
             subprocess.Popen([sys.executable, "-c",
                               "import sys; sys.exit(3)"],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)]
    sup = GangSupervisor(procs, hb, policy=_POLICY, poll=0.05,
                         label="testgang", term_grace=10.0)
    with pytest.raises(GangError) as ei:
        sup.watch(timeout=30)
    msg = str(ei.value)
    assert "rank 1 died" in msg and "rc=3" in msg
    assert "DEADLINE_EXCEEDED" in msg          # transient for relaunch
    assert "rank 0" in msg                     # per-rank diagnosis
    assert procs[0].poll() is not None, "survivor was not terminated"


def test_gang_supervisor_annotates_special_exit_codes(tmp_path):
    hb = str(tmp_path / "g.hb")
    procs = [_sleeper(),
             subprocess.Popen(
                 [sys.executable, "-c",
                  f"import sys; sys.exit({faults.EXIT_RANK_KILLED})"],
                 stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                 text=True)]
    sup = GangSupervisor(procs, hb, policy=_POLICY, poll=0.05)
    with pytest.raises(GangError, match="injected rank_kill"):
        sup.watch(timeout=30)
    assert procs[0].poll() is not None


_HB_WRITER = """
import json, os, sys, time
path = sys.argv[1]
rec = {"phase": "iter", "progress": 5, "t": time.monotonic(),
       "ka": time.monotonic(), "pid": os.getpid(), "seq": 1,
       "wall": time.time()}
with open(path, "w") as f:
    f.write(json.dumps(rec))
time.sleep(60)
"""


def test_gang_supervisor_classifies_silent_rank(tmp_path):
    """A rank that wrote one beat then went silent (keepalive dead) is
    classified within silent_sec and torn down with its phase in the
    diagnosis — never waited out to the gang deadline."""
    hb = str(tmp_path / "g.hb")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _HB_WRITER, rank_path(hb, r)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    policy = StallPolicy(stall_sec={}, default_stall=60.0,
                         silent_sec=1.0, startup_grace=20.0)
    sup = GangSupervisor(procs, hb, policy=policy, poll=0.1)
    t0 = time.monotonic()
    with pytest.raises(GangError) as ei:
        sup.watch(timeout=60)
    assert time.monotonic() - t0 < 30
    msg = str(ei.value)
    assert "classified hung" in msg and "silent" in msg
    assert "'iter'" in msg and "/5" in msg     # phase forensics
    for p in procs:
        assert p.poll() is not None


def test_gang_supervisor_success_returns_outputs(tmp_path):
    hb = str(tmp_path / "g.hb")
    procs = [subprocess.Popen(
        [sys.executable, "-c", f"print('hello {r}')"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    sup = GangSupervisor(procs, hb, policy=_POLICY, poll=0.05)
    results = sup.watch(timeout=30)
    assert [rc for rc, _ in results] == [0, 0]
    assert [out.strip() for _, out in results] == ["hello 0", "hello 1"]


# ---------------------------------------------------------------------------
# launch_local forensics + run_supervised relaunch
# ---------------------------------------------------------------------------

_ENV_HB_WRITER = """
import json, os, time
path = os.environ["LGBM_TPU_HEARTBEAT"]
rec = {"phase": "compiling", "progress": 0, "t": time.monotonic(),
       "ka": time.monotonic(), "pid": os.getpid(), "seq": 1,
       "wall": time.time()}
with open(path, "w") as f:
    f.write(json.dumps(rec))
time.sleep(60)
"""


def test_launch_local_timeout_carries_rank_diagnosis():
    """The blunt-timeout path must report per-rank last-phase/last-beat
    (the r03-style forensics gap, gang edition) — and stay catchable as
    subprocess.TimeoutExpired."""
    with pytest.raises(subprocess.TimeoutExpired) as ei:
        launch_local([sys.executable, "-c", _ENV_HB_WRITER], 1,
                     timeout=1.5)
    assert isinstance(ei.value, GangTimeout)
    msg = str(ei.value)
    assert "Per-rank diagnosis" in msg
    assert "'compiling'" in msg and "beat" in msg


_ATTEMPT_WORKER = """
import os, sys
sys.exit(2 if os.environ.get("GANG_TEST_FAIL") else 0)
"""


def test_run_supervised_relaunches_then_succeeds(tmp_path):
    """Attempt 0 dies (injected via attempt_env), attempt 1 succeeds —
    the bounded relaunch loop converges and returns rank outputs."""
    seen = []

    def attempt_env(i):
        seen.append(i)
        return {"GANG_TEST_FAIL": "1"} if i == 0 else {}

    results = gang.run_supervised(
        [sys.executable, "-c", _ATTEMPT_WORKER], 2, timeout=30,
        attempts=3, attempt_env=attempt_env, poll=0.05,
        stall_policy=_POLICY, label="testgang")
    assert [rc for rc, _ in results] == [0, 0]
    assert seen == [0, 1]


def test_run_supervised_bounded_attempts(tmp_path):
    """Every attempt failing exhausts the bounded policy and raises
    RetryError carrying the final GangError."""
    from lightgbm_tpu.robustness.retry import RetryError
    with pytest.raises(RetryError) as ei:
        gang.run_supervised(
            [sys.executable, "-c", "import sys; sys.exit(2)"], 2,
            timeout=30, attempts=2, poll=0.05, stall_policy=_POLICY,
            label="testgang")
    assert isinstance(ei.value.last, GangError)
    assert ei.value.attempts == 2


def test_gang_hb_paths_convention():
    assert gang.gang_hb_paths("/x/b.hb", 1) == ["/x/b.hb"]
    assert gang.gang_hb_paths("/x/b.hb", 2) == ["/x/b.hb.r0",
                                                "/x/b.hb.r1"]
    assert rank_path("/x/b.hb", 1) == "/x/b.hb.r1"
