"""Split-for-split parity: JAX grower vs the independent numpy reference."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset_core import BinnedDataset
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyperParams
from lightgbm_tpu.core.grower import GrowerConfig, make_tree_grower
from lightgbm_tpu.core.tree import HostTree

from ref_gbdt import HP, grow_tree_ref


def _make_data(rng, n=3000, f=6, with_nan=False):
    X = rng.normal(size=(n, f))
    # a feature with few distinct values and one sparse-ish
    X[:, 1] = rng.integers(0, 12, size=n)
    X[:, 2] = np.where(rng.random(n) < 0.7, 0.0, X[:, 2])
    if with_nan:
        X[rng.random(n) < 0.15, 3] = np.nan
    y = (X[:, 0] * 1.5 + np.sin(X[:, 1]) + X[:, 2] ** 2 * 0.3
         + rng.normal(scale=0.1, size=n))
    return X, y


def _grow_both(X, y, params, hist_backend="xla"):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    mappers = ds.used_bin_mappers()
    meta = FeatureMeta.from_mappers(mappers)
    B = int(max(m.num_bin for m in mappers))

    hp = SplitHyperParams(
        lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
        min_data_in_leaf=cfg.min_data_in_leaf,
        min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
        min_gain_to_split=cfg.min_gain_to_split,
        max_delta_step=cfg.max_delta_step, path_smooth=cfg.path_smooth)
    gcfg = GrowerConfig(num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
                        num_bin=B, hparams=hp, hist_backend=hist_backend,
                        block_rows=512)
    grow = jax.jit(make_tree_grower(gcfg, meta))

    # gradients for L2 objective from score=0
    grad = -(y.astype(np.float32))
    gh = np.stack([grad, np.ones_like(grad), np.ones_like(grad)], axis=1)
    tree, leaf_id = grow(jnp.asarray(ds.bins), jnp.asarray(gh))
    host = HostTree(jax.tree.map(np.asarray, tree), ds.used_feature_map)

    # numpy reference
    rhp = HP(lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
             min_data_in_leaf=cfg.min_data_in_leaf,
             min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
             min_gain_to_split=cfg.min_gain_to_split,
             max_delta_step=cfg.max_delta_step, path_smooth=cfg.path_smooth,
             num_leaves=cfg.num_leaves, max_depth=cfg.max_depth)
    num_bins = [m.num_bin for m in mappers]
    miss = [m.missing_type for m in mappers]
    dflt = [m.default_bin for m in mappers]
    ref_tree, ref_leaf_id = grow_tree_ref(
        np.asarray(ds.bins, np.int64), gh.astype(np.float64),
        num_bins, miss, dflt, rhp)
    return host, np.asarray(leaf_id), ref_tree, ref_leaf_id


@pytest.mark.parametrize("with_nan", [False, True])
@pytest.mark.parametrize("params", [
    {"num_leaves": 8, "min_data_in_leaf": 20},
    {"num_leaves": 16, "min_data_in_leaf": 5, "lambda_l1": 0.5,
     "lambda_l2": 1.0},
    {"num_leaves": 31, "max_depth": 4, "min_gain_to_split": 0.01},
])
def test_split_parity(rng, params, with_nan):
    X, y = _make_data(rng, with_nan=with_nan)
    host, leaf_id, ref_tree, ref_leaf_id = _grow_both(X, y, params)

    n_splits = host.num_leaves - 1
    assert n_splits == len(ref_tree.split_seq), \
        f"split count {n_splits} vs ref {len(ref_tree.split_seq)}"
    for i, (node, f, thr, dl) in enumerate(ref_tree.split_seq):
        assert host.split_feature_inner[i] == f, \
            f"split {i}: feature {host.split_feature_inner[i]} != {f}"
        assert host.threshold_bin[i] == thr, \
            f"split {i}: threshold {host.threshold_bin[i]} != {thr}"
        assert bool(host.default_left[i]) == bool(dl), f"split {i}: dl"
    # identical row partitions
    np.testing.assert_array_equal(leaf_id, ref_leaf_id)
    # leaf values close (f32 vs f64 accumulation)
    np.testing.assert_allclose(
        host.leaf_value[:host.num_leaves],
        np.asarray(ref_tree.leaf_value[:host.num_leaves]), rtol=2e-3, atol=1e-5)
    # children/parent wiring is a permutation-free exact match
    for i, nd in enumerate(ref_tree.nodes):
        assert host.left_child[i] == nd.left
        assert host.right_child[i] == nd.right


def test_hist_backends_agree(rng):
    X, y = _make_data(rng, n=1024)
    host1, l1, _, _ = _grow_both(X, y, {"num_leaves": 8}, "xla")
    host2, l2, _, _ = _grow_both(X, y, {"num_leaves": 8}, "scatter")
    np.testing.assert_array_equal(host1.split_feature_inner,
                                  host2.split_feature_inner)
    np.testing.assert_array_equal(host1.threshold_bin, host2.threshold_bin)
    np.testing.assert_array_equal(l1, l2)


@pytest.mark.slow
def test_split_parity_randomized(rng):
    """Property sweep: random hyper-parameter combinations must stay
    split-for-split identical to the numpy oracle (broadens the fixed
    configs above across the L1/L2/depth/min-data/smoothing space)."""
    for trial in range(8):
        trng = np.random.default_rng(1000 + trial)
        params = {
            "num_leaves": int(trng.choice([4, 8, 15, 31])),
            "max_depth": int(trng.choice([-1, 3, 5])),
            "min_data_in_leaf": int(trng.choice([1, 5, 25, 80])),
            "lambda_l1": float(trng.choice([0.0, 0.3, 2.0])),
            "lambda_l2": float(trng.choice([0.0, 1.0, 10.0])),
            "min_gain_to_split": float(trng.choice([0.0, 0.05])),
            "max_delta_step": float(trng.choice([0.0, 0.5])),
            "path_smooth": float(trng.choice([0.0, 1.0])),
            "max_bin": int(trng.choice([15, 63, 255])),
        }
        X, y = _make_data(trng, n=1200, f=5,
                          with_nan=bool(trng.integers(0, 2)))
        host, leaf_id, ref_tree, ref_leaf_id = _grow_both(X, y, params)
        assert host.num_leaves - 1 == len(ref_tree.split_seq), \
            (trial, params)
        for i, (node, f, thr, dl) in enumerate(ref_tree.split_seq):
            assert host.split_feature_inner[i] == f, (trial, params, i)
            assert host.threshold_bin[i] == thr, (trial, params, i)
        np.testing.assert_array_equal(leaf_id, ref_leaf_id,
                                      err_msg=str((trial, params)))
