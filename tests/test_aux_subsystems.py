"""Auxiliary subsystems (SURVEY §5): jax.profiler tracing hook, the
multi-host entry points, and the generated parameter docs."""
import pytest
import os

import sys

import numpy as np

import lightgbm_tpu as lgb


@pytest.mark.slow
def test_profiler_trace_capture(rng, tmp_path):
    X = rng.normal(size=(2000, 6))
    y = X[:, 0]
    d = str(tmp_path / "trace")
    lgb.train({"objective": "regression", "verbose": -1,
               "tpu_profile_dir": d}, lgb.Dataset(X, label=y),
              num_boost_round=3)
    files = [f for _, _, fs in os.walk(d) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in files)


def test_timer_table(rng):
    from lightgbm_tpu.utils.timer import global_timer
    was = global_timer.enabled
    try:
        global_timer.enabled = True
        global_timer.reset()
        X = rng.normal(size=(1000, 4))
        lgb.train({"objective": "regression", "verbose": -1},
                  lgb.Dataset(X, label=X[:, 0]), num_boost_round=2)
        table = global_timer.table()
        assert "TreeLearner::Train" in table
        assert "GBDT::Boosting" in table
    finally:
        global_timer.enabled = was
        global_timer.reset()


def test_distributed_module_surface():
    from lightgbm_tpu import distributed

    assert callable(distributed.init_distributed)
    assert callable(distributed.shutdown_distributed)
    # without init, helpers still answer for the single-process world
    assert distributed.num_processes() >= 1
    assert distributed.process_index() >= 0


def test_parameter_docs_in_sync():
    """docs/Parameters.md must regenerate identically from the registry
    (no filesystem mutation: compare against main()'s returned text)."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    sys.path.insert(0, os.path.join(repo, "docs"))
    try:
        import gen_parameters
        fresh = gen_parameters.main()
    finally:
        sys.path.pop(0)
    committed = open(os.path.join(repo, "docs", "Parameters.md")).read()
    assert committed == fresh, \
        "docs/Parameters.md is stale; rerun docs/gen_parameters.py"


def test_debug_checks_env_flag(tmp_path):
    """LIGHTGBM_TPU_DEBUG_CHECKS turns on the jax sanitizers (SURVEY §5
    race/sanitizer analogue): NaN production inside jitted code fails
    loudly instead of corrupting training downstream."""
    import subprocess
    import sys
    code = (
        "import os\n"
        "os.environ['LIGHTGBM_TPU_DEBUG_CHECKS'] = '1'\n"
        "os.environ['LIGHTGBM_TPU_PLATFORM'] = 'cpu'\n"
        "import lightgbm_tpu  # activates the flags\n"
        "import jax, jax.numpy as jnp\n"
        "assert jax.config.jax_debug_nans\n"
        "assert jax.config.jax_check_tracer_leaks\n"
        "try:\n"
        "    jax.jit(lambda x: x / 0.0 * 0.0)(jnp.float32(1.0))\n"
        "except FloatingPointError:\n"
        "    print('SANITIZER-OK')\n"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=300)
    assert "SANITIZER-OK" in out.stdout, (out.stdout, out.stderr)
