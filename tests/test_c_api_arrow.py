"""Arrow C-data-interface entry points of the native ABI.

pyarrow exports real spec-ABI structs (RecordBatch._export_to_c /
RecordBatchReader._export_to_c), which is exactly what an embedding
host hands to the reference's nanoarrow layer — so these tests drive
LGBM_DatasetCreateFromArrow(Stream) / SetFieldFromArrow /
PredictForArrow(Stream) with genuine Arrow memory, including nulls
(-> NaN missing values) and mixed column dtypes.
"""
import ctypes
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import lightgbm_tpu as lgb
from lightgbm_tpu.native import get_lib

# spec struct sizes on LP64: ArrowSchema 72 B, ArrowArray 88 B,
# ArrowArrayStream 40 B — allocate raw, pyarrow fills them
_SCHEMA_SZ, _ARRAY_SZ, _STREAM_SZ = 72, 88, 40


def _export_batch(batch):
    sbuf = ctypes.create_string_buffer(_SCHEMA_SZ)
    abuf = ctypes.create_string_buffer(_ARRAY_SZ)
    batch._export_to_c(ctypes.addressof(abuf), ctypes.addressof(sbuf))
    return abuf, sbuf


def _export_reader(reader):
    stbuf = ctypes.create_string_buffer(_STREAM_SZ)
    reader._export_to_c(ctypes.addressof(stbuf))
    return stbuf


@pytest.fixture(scope="module")
def lib():
    lib = get_lib()
    assert lib is not None
    os.environ.setdefault("LIGHTGBM_TPU_PLATFORM", "cpu")
    return lib


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    n = 500
    x0 = rng.normal(size=n)
    x1 = rng.integers(0, 100, size=n).astype(np.int32)
    x2 = rng.normal(size=n).astype(np.float32)
    y = (x0 * 2 - x1 * 0.01 + rng.normal(scale=0.1, size=n)).astype(
        np.float32)
    # column 2 carries nulls -> NaN missing values
    mask = rng.uniform(size=n) < 0.1
    tbl = pa.table({
        "a": pa.array(x0),
        "b": pa.array(x1),
        "c": pa.array(np.where(mask, np.nan, x2), mask=mask),
    })
    X = np.column_stack([x0, x1.astype(np.float64),
                         np.where(mask, np.nan, x2)])
    return tbl, X, y


def _train_via_arrow(lib, tbl, y, streaming):
    ds = ctypes.c_void_p()
    params = b"max_bin=63 min_data_in_leaf=5 verbosity=-1 device_type=cpu"
    if streaming:
        st = _export_reader(pa.RecordBatchReader.from_batches(
            tbl.schema, tbl.to_batches(max_chunksize=120)))
        rc = lib.LGBM_DatasetCreateFromArrowStream(
            ctypes.c_void_p(ctypes.addressof(st)), params, None, ctypes.byref(ds))
    else:
        batch = tbl.combine_chunks().to_batches()[0]
        abuf, sbuf = _export_batch(batch)
        rc = lib.LGBM_DatasetCreateFromArrow(
            ctypes.c_int64(1), ctypes.c_void_p(ctypes.addressof(abuf)),
            ctypes.c_void_p(ctypes.addressof(sbuf)), params, None, ctypes.byref(ds))
    assert rc == 0, lib.LGBM_GetLastError()

    # label through the Arrow field path too
    lbl = pa.record_batch({"y": pa.array(y)})
    la, ls = _export_batch(lbl)
    rc = lib.LGBM_DatasetSetFieldFromArrow(
        ds, b"label", ctypes.c_int64(1), ctypes.c_void_p(ctypes.addressof(la)),
        ctypes.c_void_p(ctypes.addressof(ls)))
    assert rc == 0, lib.LGBM_GetLastError()

    bst = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(
        ds, b"objective=regression num_leaves=15 min_data_in_leaf=5 "
            b"verbosity=-1 device_type=cpu", ctypes.byref(bst))
    assert rc == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int(0)
    for _ in range(6):
        assert lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    return ds, bst


def test_arrow_create_train_predict(lib, data):
    tbl, X, y = data
    ds, bst = _train_via_arrow(lib, tbl, y, streaming=False)

    n, f = X.shape
    out_mat = np.zeros(n)
    out_len = ctypes.c_int64(0)
    Xc = np.ascontiguousarray(X)
    rc = lib.LGBM_BoosterPredictForMat(
        bst, Xc.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out_mat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()

    # Arrow prediction path must match the dense path exactly
    batch = tbl.combine_chunks().to_batches()[0]
    abuf, sbuf = _export_batch(batch)
    out_arrow = np.zeros(n)
    rc = lib.LGBM_BoosterPredictForArrow(
        bst, ctypes.c_int64(1), ctypes.c_void_p(ctypes.addressof(abuf)),
        ctypes.c_void_p(ctypes.addressof(sbuf)), 0, 0, -1, b"", ctypes.byref(out_len),
        out_arrow.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    np.testing.assert_allclose(out_arrow, out_mat, rtol=1e-9)
    # the model learned the signal
    assert np.mean((out_mat - y) ** 2) < np.var(y) * 0.5
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_arrow_stream_create_and_predict(lib, data):
    tbl, X, y = data
    ds, bst = _train_via_arrow(lib, tbl, y, streaming=True)
    n = X.shape[0]
    out_len = ctypes.c_int64(0)
    out_stream = np.zeros(n)
    st = _export_reader(pa.RecordBatchReader.from_batches(
        tbl.schema, tbl.to_batches(max_chunksize=77)))
    rc = lib.LGBM_BoosterPredictForArrowStream(
        bst, ctypes.c_void_p(ctypes.addressof(st)), 0, 0, -1, b"",
        ctypes.byref(out_len),
        out_stream.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == n
    assert np.isfinite(out_stream).all()
    assert np.mean((out_stream - y) ** 2) < np.var(y) * 0.5
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_arrow_unsupported_format_errors(lib):
    tbl = pa.table({"s": pa.array(["a", "b", "c"])})
    batch = tbl.to_batches()[0]
    abuf, sbuf = _export_batch(batch)
    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromArrow(
        ctypes.c_int64(1), ctypes.c_void_p(ctypes.addressof(abuf)),
        ctypes.c_void_p(ctypes.addressof(sbuf)), b"", None, ctypes.byref(ds))
    assert rc != 0
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    assert b"format" in lib.LGBM_GetLastError()
