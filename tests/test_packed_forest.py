"""Packed-forest serving engine (ISSUE 5): depth-bounded traversal,
device-side binning, incremental packing, batch bucketing, the raw
(loaded-model) route, the model-generation counter, and the sklearn
``device=`` passthrough."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.core.tree import host_tree_to_arrays, max_leaf_depth
from lightgbm_tpu.ops import forest as forest_mod
from lightgbm_tpu.ops.forest import (DeviceBinner, bucket_rows, f32_floor,
                                     _host_tree_to_raw)
from lightgbm_tpu.ops.predict import (depth_steps, forest_leaf_bins,
                                      tree_leaf_bins, tree_leaf_raw)


def _train(rng, n=600, f=6, missing=None, n_round=8, cat=False, **params):
    X = rng.normal(size=(n, f)).astype(np.float32).astype(np.float64)
    kw = {}
    if missing == "nan":
        X[rng.uniform(size=X.shape) < 0.08] = np.nan
    elif missing == "zero":
        X[rng.uniform(size=X.shape) < 0.15] = 0.0
        kw["zero_as_missing"] = True
    elif missing == "none":
        kw["use_missing"] = False
    if cat:
        X[:, f - 1] = rng.integers(0, 8, size=n)
    y = np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1])
    p = {"objective": "regression", "num_leaves": 31, "verbose": -1,
         "min_data_in_leaf": 5, **kw, **params}
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=[f - 1] if cat else "auto")
    return lgb.train(p, ds, num_boost_round=n_round), X


def _adversarial(rng, X):
    """Request batch exercising NaN, exact zeros, +-inf and the
    kZeroThreshold edge (float32(1e-35) rounds UP past 1e-35 — the value
    that misroutes at zero-missing nodes if the device compares against
    a naively-cast constant)."""
    Xq = X.copy()
    n = len(Xq)
    Xq[: n // 8] = np.nan
    Xq[n // 8: n // 4] = 0.0
    Xq[n // 4: 3 * n // 8] = np.inf
    Xq[3 * n // 8: n // 2] = -np.inf
    zt = np.float32(1e-35).astype(np.float64)     # > 1e-35, f32-exact
    Xq[n // 2: 9 * n // 16] = zt
    Xq[9 * n // 16: 5 * n // 8] = -zt
    return Xq


def _engine_meta(eng):
    from lightgbm_tpu.ops.split import FeatureMeta
    return FeatureMeta.from_mappers(eng.train_set.used_bin_mappers())


# ---------------------------------------------------------------------------
# depth-bounded traversal
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_depth_bounded_identical_to_exhaustive_on_ragged_forest(rng):
    """Trees of different depths (natural raggedness from min_data
    constraints): the depth-bounded loop must land every row in exactly
    the leaf the L-1 exhaustive loop lands it in."""
    import jax.numpy as jnp
    bst, X = _train(rng, n=900, n_round=10, num_leaves=63)
    eng = bst._engine
    meta = _engine_meta(eng)
    bins_dev = jnp.asarray(eng.train_set.ensure_logical_bins()
                           if eng.train_set.bins is None
                           else eng.train_set.bins)
    L = eng.config.num_leaves
    depths = [t.max_depth for t in eng.models]
    assert len(set(depths)) > 1, "forest is not ragged — weak test data"
    assert max(depths) < L - 1
    for t in eng.models:
        arrs = host_tree_to_arrays(t, L)
        assert int(arrs.max_depth) == t.max_depth
        exhaustive = tree_leaf_bins(arrs, bins_dev, meta.num_bin,
                                    meta.missing_type, meta.default_bin,
                                    num_steps=L - 1)
        bounded = tree_leaf_bins(arrs, bins_dev, meta.num_bin,
                                 meta.missing_type, meta.default_bin,
                                 num_steps=depth_steps(t.max_depth, L))
        np.testing.assert_array_equal(np.asarray(exhaustive),
                                      np.asarray(bounded))


def test_max_leaf_depth_units():
    # root splits into two leaves: 1 decision
    assert max_leaf_depth([-1], [-2], 2) == 1
    # chain: node0 -> (leaf, node1), node1 -> (leaf, leaf)
    assert max_leaf_depth([-1, -2], [1, -3], 3) == 2
    assert max_leaf_depth([], [], 1) == 0
    # corrupted (cyclic) pointers fall back to the exhaustive bound
    assert max_leaf_depth([1, 0], [1, 0], 3) == 2


def test_depth_steps_bucketing():
    assert depth_steps(0, 255) == 0
    assert depth_steps(1, 255) == 4
    assert depth_steps(13, 255) == 16
    assert depth_steps(16, 255) == 16
    assert depth_steps(17, 255) == 20
    assert depth_steps(999, 255) == 254
    assert depth_steps(None, 255) == 254


# ---------------------------------------------------------------------------
# parity matrix: leaf-identical across missing types and adversarial values
# ---------------------------------------------------------------------------

# one fast representative (nan: the adversarial missing type); the
# other two cells behind -m slow (predict_smoke.py gates all three
# missing types every check.sh run)
@pytest.mark.parametrize("missing", [
    pytest.param("none", marks=pytest.mark.slow),
    pytest.param("zero", marks=pytest.mark.slow), "nan"])
def test_leaf_parity_matrix_binned_and_raw(rng, missing):
    """Bit-identical per-tree LEAF INDICES between the host walk, the
    device binned route (device binning + forest_leaf_bins) and the raw
    route (tree_leaf_raw over f32_floor thresholds), with NaN, zeros and
    +-inf in the request batch."""
    import jax.numpy as jnp
    bst, X = _train(rng, missing=missing, n_round=6)
    eng = bst._engine
    Xq = _adversarial(rng, X)
    L = eng.config.num_leaves
    mappers = eng.train_set.used_bin_mappers()
    binner = DeviceBinner(mappers, eng.train_set.used_feature_map)
    bins_dev = binner.bins(Xq)
    meta = _engine_meta(eng)
    pack = forest_mod.ForestPack(L)
    pack.sync(eng.models, gen=0, mappers=mappers)
    for i, t in enumerate(eng.models):
        host_leaf = t.predict_leaf(Xq)
        arrs = host_tree_to_arrays(t, L)
        # generic binned body over DEVICE-computed bins
        dev_generic = tree_leaf_bins(arrs, bins_dev, meta.num_bin,
                                     meta.missing_type, meta.default_bin)
        np.testing.assert_array_equal(host_leaf, np.asarray(dev_generic))
        # serving body (special/flip folded at pack time)
        import jax
        p = jax.tree.map(lambda x: x[i], pack.stacked)
        dev_serving = forest_leaf_bins(
            p.tree, p.special, p.flip, bins_dev,
            num_steps=depth_steps(t.max_depth, L))
        np.testing.assert_array_equal(host_leaf, np.asarray(dev_serving))
        # raw route (per-node missing from decision_type)
        raw = _host_tree_to_raw(t, L)
        dev_raw = tree_leaf_raw(raw, jnp.asarray(Xq, jnp.float32))
        np.testing.assert_array_equal(host_leaf, np.asarray(dev_raw))


def test_f64_only_requests_never_misroute(rng):
    """A request value one f64-ulp above a bin bound rounds BELOW it in
    f32 (the observed sklearn flake): the binned route must re-bin such
    columns with the host mapper, the raw route must refuse and fall
    back — device and host predictions stay identical either way."""
    bst, X = _train(rng, n_round=5)
    eng = bst._engine
    m = eng.train_set.used_bin_mappers()[0]
    b = float(m.bin_upper_bound[len(m.bin_upper_bound) // 2])
    Xq = X.copy()
    Xq[:, 0] = np.nextafter(b, np.inf)           # f64-only, straddles in f32
    assert np.float32(Xq[0, 0]).astype(np.float64) != Xq[0, 0]
    host = bst.predict(Xq, raw_score=True)
    dev = bst.predict(Xq, device=True, raw_score=True)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)
    # per-tree leaf parity (bit-identical) through the serving engine
    mappers = eng.train_set.used_bin_mappers()
    binner = DeviceBinner(mappers, eng.train_set.used_feature_map)
    bins_dev = np.asarray(binner.bins(Xq))
    for i, (fi, mp) in enumerate(zip(eng.train_set.used_feature_map,
                                     mappers)):
        np.testing.assert_array_equal(
            bins_dev[i], mp.value_to_bin(np.asarray(Xq[:, fi])))
    # raw route refuses f64-only values -> loaded booster host fallback
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_array_equal(loaded.predict(Xq, device=True),
                                  loaded.predict(Xq))


def test_f32_floor_exact_boundary():
    v = np.asarray([1.0, 1.0 + 1e-12, -1.0 - 1e-12, np.inf, -np.inf,
                    1e300, -1e300, 0.0])
    out = f32_floor(v)
    assert out.dtype == np.float32
    # the defining property: f64(out) <= v, and the next f32 up is > v
    ok = np.isfinite(v)
    assert (out[ok].astype(np.float64) <= v[ok]).all()
    nxt = np.nextafter(out[ok], np.float32(np.inf))
    assert (nxt.astype(np.float64) > v[ok]).all()
    assert out[3] == np.inf and out[4] == -np.inf


@pytest.mark.slow
def test_device_binning_matches_host_mapper(rng):
    bst, X = _train(rng, missing="nan", cat=True, n_round=3)
    eng = bst._engine
    Xq = _adversarial(rng, X)
    mappers = eng.train_set.used_bin_mappers()
    used = eng.train_set.used_feature_map
    binner = DeviceBinner(mappers, used)
    dev = np.asarray(binner.bins(Xq))
    for i, (fi, m) in enumerate(zip(used, mappers)):
        host = m.value_to_bin(np.asarray(Xq[:, fi], np.float64))
        np.testing.assert_array_equal(dev[i], host, err_msg=f"feature {fi}")


# ---------------------------------------------------------------------------
# stale cache (satellite 1) + generation counter
# ---------------------------------------------------------------------------

def test_stale_cache_after_rollback_and_retrain(rng):
    """THE regression: predict(device) -> rollback_one_iter -> retrain
    back to the SAME model count with different gradients. A cache keyed
    only on (window, len(models)) serves the pre-rollback forest; the
    generation counter must not."""
    X = rng.normal(size=(400, 5))
    y = X[:, 0] * 2 + rng.normal(scale=0.1, size=400)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster({"objective": "regression", "num_leaves": 15,
                       "verbose": -1, "min_data_in_leaf": 5}, ds)
    for _ in range(3):
        bst.update()
    before = bst.predict(X, device=True)
    bst.rollback_one_iter()

    def fobj(preds, _):
        grad = np.asarray(preds - y * 3.0, np.float32)  # NOT the mse grad
        return grad, np.ones_like(grad)

    bst.update(fobj=fobj)
    assert bst.current_iteration() == 3          # same count as before
    host = bst.predict(X)
    dev = bst.predict(X, device=True)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)
    assert np.abs(dev - before).max() > 1e-4, \
        "retrained tree is indistinguishable — the regression cannot bite"


def test_model_generation_counter_semantics(rng):
    bst, X = _train(rng, n_round=3)
    eng = bst._engine
    g0 = eng._model_gen
    eng.models.append(eng.models[0].copy())      # tail append: NO bump
    assert eng._model_gen == g0
    del eng.models[-1:]                          # destructive: bump
    assert eng._model_gen > g0
    g1 = eng._model_gen
    eng.models[0] = eng.models[0].copy()         # replacement: bump
    assert eng._model_gen > g1
    g2 = eng._model_gen
    eng.invalidate_serving_cache()               # in-place content edit
    assert eng._model_gen > g2
    g3 = eng._model_gen
    eng.models = list(eng.models)                # wholesale assignment
    assert eng._model_gen > g3


def test_incremental_pack_appends_only_new_trees(rng, monkeypatch):
    bst, X = _train(rng, n_round=3)
    eng = bst._engine
    calls = []
    orig = forest_mod.ForestPack._pack_tree

    def spy(self, t):
        calls.append(t)
        return orig(self, t)

    monkeypatch.setattr(forest_mod.ForestPack, "_pack_tree", spy)
    bst.predict(X, device=True)
    assert len(calls) == 3
    pack = eng._serving.pack
    assert pack.count == 3
    gen_after_first = pack.gen
    for _ in range(2):
        bst.update()                             # appends, no gen bump
    bst.predict(X, device=True)
    assert len(calls) == 5, "window growth restacked the whole forest"
    assert pack.count == 5 and pack.gen == gen_after_first
    # narrower window: same pack, sliced — no new tree packing
    bst.predict(X, device=True, num_iteration=2)
    assert len(calls) == 5


# ---------------------------------------------------------------------------
# batch bucketing + compile budget
# ---------------------------------------------------------------------------

def test_bucket_rows_properties():
    sizes = list(range(1, 20001, 7))
    buckets = {bucket_rows(r) for r in sizes}
    assert all(bucket_rows(r) >= r for r in sizes)
    assert len(buckets) < 30
    for r in sizes:
        if r > 4096:
            assert bucket_rows(r) / r <= 1.15
    # idempotent: a bucket maps to itself
    for b in buckets:
        assert bucket_rows(b) == b


def test_mixed_size_predict_compile_budget(rng):
    """Steady state: after warming the (few) buckets, 5 mixed-size
    predict calls must not trace a single new program."""
    bst, X = _train(rng, n_round=4)
    for warm in (500, 140):                      # buckets 512 and 256
        bst.predict(X[:warm], device=True)
    with guards.CompileCounter() as counter:
        for r in (500, 400, 300, 140, 450):
            bst.predict(X[:r], device=True)
    assert counter.count == 0, counter.names


def test_bucketing_off_exact_shapes(rng):
    bst, X = _train(rng, n_round=2, tpu_predict_buckets=False)
    host = bst.predict(X[:123])
    dev = bst.predict(X[:123], device=True)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# raw route: loaded model without mappers (satellite 2)
# ---------------------------------------------------------------------------

def test_loaded_model_serves_on_device(rng):
    bst, X = _train(rng, missing="nan", n_round=5)
    Xq = _adversarial(rng, X)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    host = loaded.predict(Xq, raw_score=True)
    dev = loaded.predict(Xq, device=True, raw_score=True)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)
    # the device path actually ran (no silent host fallback)
    eng = loaded._engine
    assert eng._serving is not None
    assert eng._serving.raw_pack.count == len(eng.models)


def test_loaded_categorical_model_falls_back_to_host(rng):
    n = 600
    X = rng.normal(size=(n, 4))
    X[:, 3] = rng.integers(0, 6, size=n)
    y = (X[:, 3] % 2) * 3.0 + X[:, 0]            # cat splits are learned
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[3]),
                    num_boost_round=4)
    assert any(t.num_cat > 0 for t in bst._engine.models)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    host = loaded.predict(X)
    dev = loaded.predict(X, device=True)         # warns, host path
    np.testing.assert_array_equal(dev, host)
    assert loaded._engine._serving is None       # raw route refused


def test_raw_servability_is_window_scoped(rng):
    """One categorical tree OUTSIDE the requested window must not defeat
    device serving for a servable window (packing is tolerant; the
    servability check applies to the window, not the whole list)."""
    import pytest as _pytest
    n = 500
    Xc = rng.normal(size=(n, 4))
    Xc[:, 3] = rng.integers(0, 6, size=n)
    bst_cat = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbose": -1, "min_data_in_leaf": 5},
                        lgb.Dataset(Xc, label=(Xc[:, 3] % 2) * 3.0,
                                    categorical_feature=[3]),
                        num_boost_round=1)
    bst_num, X = _train(rng, n_round=1)
    cat_tree = bst_cat._engine.models[0]
    num_tree = bst_num._engine.models[0]
    assert cat_tree.num_cat > 0
    srv = forest_mod.ServingEngine(31, 1)
    with _pytest.raises(ValueError):
        srv.predict_raw([cat_tree, num_tree], 0, X, 0, 2)
    out = srv.predict_raw([cat_tree, num_tree], 0, X, 1, 2)
    np.testing.assert_allclose(out[0], num_tree.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_loaded_model_set_leaf_output_invalidates(rng):
    bst, X = _train(rng, n_round=3)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    before = loaded.predict(X, device=True, raw_score=True)
    loaded.set_leaf_output(0, 0, loaded.get_leaf_output(0, 0) + 7.0)
    after = loaded.predict(X, device=True, raw_score=True)
    host = loaded.predict(X, raw_score=True)
    np.testing.assert_allclose(after, host, rtol=1e-5, atol=1e-6)
    assert np.abs(after - before).max() > 1.0


# ---------------------------------------------------------------------------
# sklearn passthrough (satellite 6)
# ---------------------------------------------------------------------------

def test_sklearn_device_passthrough(rng):
    X = rng.normal(size=(400, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    clf = lgb.LGBMClassifier(n_estimators=6, num_leaves=15, verbose=-1,
                             min_child_samples=5)
    clf.fit(X, y)
    proba_host = clf.predict_proba(X)
    proba_dev = clf.predict_proba(X, device=True)
    # f32 raw-margin accumulation passes through the sigmoid: tolerance
    # is on the margin, not the leaf decisions (leaf parity is exact)
    np.testing.assert_allclose(proba_dev, proba_host, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(clf.predict(X, device=True),
                                  clf.predict(X))
    reg = lgb.LGBMRegressor(n_estimators=6, num_leaves=15, verbose=-1,
                            min_child_samples=5)
    reg.fit(X, X[:, 0])
    np.testing.assert_allclose(reg.predict(X, device=True), reg.predict(X),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# multiclass window arithmetic through the packed engine
# ---------------------------------------------------------------------------

def test_multiclass_windows_and_iteration_ranges(rng):
    n = 500
    X = rng.normal(size=(n, 6))
    y = (np.abs(X[:, 0]) + np.abs(X[:, 1]) * 2).astype(int) % 3
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbose": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    for kw in ({}, {"num_iteration": 3},
               {"start_iteration": 2, "num_iteration": 3}):
        host = bst.predict(X, **kw)
        dev = bst.predict(X, device=True, **kw)
        np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# bench record shapes (the inference metric's status grammar)
# ---------------------------------------------------------------------------

def test_bench_predict_record_grammar():
    import importlib
    import json
    bench = importlib.import_module("bench")
    rec = bench._predict_record(1234.5, sched="compact")
    assert rec["metric"].endswith("_predict_rows_per_sec") or \
        "_predict_rows_per_sec" in rec["metric"]
    assert rec["unit"] == "rows/sec"
    fail = json.loads(bench._predict_fail_line(
        "x", status="device_unreachable"))
    assert fail["status"] == "device_unreachable"
    assert fail["value"] == 0.0
    assert "_predict_rows_per_sec" in fail["metric"]
