"""Native C API serving: LGBM_BoosterCreateFromModelfile + PredictForMat
must reproduce the Python Booster's predictions bit-for-bit on saved
models — numerical/categorical splits, NaN routing, multiclass softmax,
linear trees, leaf indices, iteration windows (ref: include/LightGBM/
c_api.h prediction subset)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.native import get_lib

pytestmark = pytest.mark.skipif(get_lib() is None,
                                reason="no native toolchain")


def _native(path):
    from lightgbm_tpu.native.capi import NativeBooster
    return NativeBooster(model_file=path)


def _train_save(tmp_path, params, X, y, rounds=10, **ds_kw):
    bst = lgb.train(dict(params, verbose=-1, min_data_in_leaf=5),
                    lgb.Dataset(X, label=y, **ds_kw),
                    num_boost_round=rounds)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    return bst, path


def test_regression_parity(rng, tmp_path):
    X = rng.normal(size=(400, 8)).astype(np.float64)
    y = X[:, 0] * 2 + np.sin(X[:, 1])
    bst, path = _train_save(tmp_path, {"objective": "regression"}, X, y)
    nb = _native(path)
    assert nb.num_iterations == 10
    assert nb.num_features == 8
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-12, atol=1e-12)


def test_binary_sigmoid_and_raw(rng, tmp_path):
    X = rng.normal(size=(500, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    bst, path = _train_save(tmp_path, {"objective": "binary"}, X, y)
    nb = _native(path)
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(nb.predict(X, raw_score=True),
                               bst.predict(X, raw_score=True),
                               rtol=1e-12, atol=1e-12)


def test_multiclass_softmax(rng, tmp_path):
    k = 4
    centers = rng.normal(scale=2.0, size=(k, 5))
    yid = rng.integers(0, k, size=600)
    X = centers[yid] + rng.normal(size=(600, 5))
    bst, path = _train_save(tmp_path,
                            {"objective": "multiclass", "num_class": k},
                            X, yid.astype(np.float32))
    nb = _native(path)
    assert nb.num_classes == k
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-10, atol=1e-12)


def test_categorical_and_nan(rng, tmp_path):
    n = 600
    X = rng.normal(size=(n, 5))
    X[:, 2] = rng.integers(0, 10, size=n)
    X[rng.uniform(size=n) < 0.1, 0] = np.nan       # missing values
    y = ((X[:, 2] % 3 == 1) | (np.nan_to_num(X[:, 0]) > 1)).astype(
        np.float32)
    bst, path = _train_save(tmp_path, {"objective": "binary"}, X, y,
                            categorical_feature=[2])
    nb = _native(path)
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-12, atol=1e-12)
    # unseen category and all-NaN row route like the Python path
    X2 = X[:5].copy()
    X2[0, 2] = 99
    X2[1, :] = np.nan
    np.testing.assert_allclose(nb.predict(X2), bst.predict(X2),
                               rtol=1e-12, atol=1e-12)


def test_linear_tree_parity(rng, tmp_path):
    X = rng.normal(size=(500, 4))
    y = 3 * X[:, 0] + X[:, 1] + 0.05 * rng.normal(size=500)
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True})
    bst = lgb.train({"objective": "regression", "linear_tree": True,
                     "linear_lambda": 0.1, "verbose": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=10)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    nb = _native(path)
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-10, atol=1e-10)


def test_leaf_index_and_iteration_window(rng, tmp_path):
    X = rng.normal(size=(300, 6))
    y = X[:, 0] - X[:, 1]
    bst, path = _train_save(tmp_path, {"objective": "regression"}, X, y)
    nb = _native(path)
    np.testing.assert_array_equal(nb.predict(X, pred_leaf=True),
                                  bst.predict(X, pred_leaf=True))
    np.testing.assert_allclose(
        nb.predict(X, raw_score=True, start_iteration=2, num_iteration=5),
        bst.predict(X, raw_score=True, start_iteration=2, num_iteration=5),
        rtol=1e-12, atol=1e-12)


def test_model_from_string(rng, tmp_path):
    from lightgbm_tpu.native.capi import NativeBooster
    X = rng.normal(size=(200, 4))
    y = X[:, 0]
    bst, path = _train_save(tmp_path, {"objective": "regression"}, X, y,
                            rounds=3)
    nb = NativeBooster(model_str=bst.model_to_string())
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-12, atol=1e-12)


def test_rf_average_output(rng, tmp_path):
    X = rng.normal(size=(400, 6))
    y = (X[:, 0] > 0).astype(np.float32)
    bst, path = _train_save(tmp_path,
                            {"objective": "binary", "boosting": "rf",
                             "bagging_freq": 1, "bagging_fraction": 0.7},
                            X, y)
    nb = _native(path)
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-12, atol=1e-12)


def test_special_transforms(rng, tmp_path):
    X = rng.normal(size=(300, 5))
    y = (X[:, 0] + 2.5) ** 2
    bst, path = _train_save(tmp_path,
                            {"objective": "regression", "reg_sqrt": True},
                            X, np.abs(y))
    nb = _native(path)
    np.testing.assert_allclose(nb.predict(X), bst.predict(X),
                               rtol=1e-10, atol=1e-10)
    y2 = rng.uniform(0.0, 1.0, size=300)
    bst2, path2 = _train_save(tmp_path, {"objective": "xentlambda"}, X, y2)
    nb2 = _native(path2)
    np.testing.assert_allclose(nb2.predict(X), bst2.predict(X),
                               rtol=1e-10, atol=1e-12)


def test_garbage_model_rejected():
    from lightgbm_tpu.native.capi import NativeBooster
    with pytest.raises(RuntimeError, match="parse"):
        NativeBooster(model_str="hello world\nnot a model\n")


def test_reference_golden_model():
    # a model TRAINED BY THE REFERENCE CLI must serve identically through
    # the native C path (empty CSV fields are missing values)
    import os
    golden = os.path.join(os.path.dirname(__file__), "data", "golden")
    rows = []
    with open(os.path.join(golden, "test.csv")) as fh:
        for line in fh:
            rows.append([np.nan if v == "" else float(v)
                         for v in line.rstrip("\n").split(",")])
    X = np.asarray(rows, np.float64)[:, 1:]
    expect = np.loadtxt(os.path.join(golden, "pred.txt"))
    nb = _native(os.path.join(golden, "model.txt"))
    np.testing.assert_allclose(nb.predict(X), expect, rtol=1e-9, atol=1e-12)


def test_predict_for_csr(rng, tmp_path):
    """Native CSR prediction (no densify): parity with the dense path
    (ref: c_api.cpp PredictForCSR / RowFunctionFromCSR)."""
    import ctypes
    import scipy.sparse as sp

    X = np.zeros((300, 12))
    mask = rng.uniform(size=X.shape) < 0.2
    X[mask] = rng.normal(size=int(mask.sum()))
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float64)
    bst, path = _train_save(tmp_path, {"objective": "binary"}, X, y)

    lib = get_lib()
    handle = ctypes.c_void_p()
    n_iter = ctypes.c_int()
    assert lib.LGBM_BoosterCreateFromModelfile(
        path.encode(), ctypes.byref(n_iter), ctypes.byref(handle)) == 0
    csr = sp.csr_matrix(X)
    indptr = np.asarray(csr.indptr, np.int32)
    indices = np.asarray(csr.indices, np.int32)
    data = np.asarray(csr.data, np.float64)
    out = np.zeros(300, np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBM_BoosterPredictForCSR(
        handle,
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(X.shape[1]), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(0), b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    assert out_len.value == 300
    np.testing.assert_allclose(out, bst.predict(X), rtol=1e-6, atol=1e-9)
    lib.LGBM_BoosterFree(handle)
