"""sklearn-estimator and plotting tests (ref: tests/python_package_test/
test_sklearn.py, test_plotting.py — condensed to the behavioral core)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import (LGBMClassifier, LGBMModel, LGBMRanker,
                          LGBMRegressor)


def _make_reg(rng, n=400, f=8):
    X = rng.normal(size=(n, f))
    y = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.05 * rng.normal(size=n)
    return X, y


def test_regressor_fit_predict(rng):
    X, y = _make_reg(rng)
    model = LGBMRegressor(n_estimators=20, num_leaves=15,
                          min_child_samples=5)
    model.fit(X, y)
    pred = model.predict(X)
    assert 1 - np.var(y - pred) / np.var(y) > 0.8
    assert model.n_features_ == 8
    assert len(model.feature_importances_) == 8
    assert model.feature_importances_.sum() > 0
    assert model.objective_ == "regression"


def test_regressor_eval_set_and_early_stopping(rng):
    X, y = _make_reg(rng)
    Xv, yv = _make_reg(rng, n=100)
    model = LGBMRegressor(n_estimators=50, num_leaves=15,
                          min_child_samples=5)
    model.fit(X, y, eval_set=[(Xv, yv)],
              callbacks=[lgb.early_stopping(5, verbose=False)])
    assert "valid_0" in model.evals_result_
    assert "l2" in model.evals_result_["valid_0"]
    assert model.best_iteration_ >= 1


def test_binary_classifier(rng):
    X, y = _make_reg(rng)
    yc = (y > np.median(y)).astype(int)
    model = LGBMClassifier(n_estimators=20, num_leaves=15,
                           min_child_samples=5)
    model.fit(X, yc)
    assert (model.predict(X) == yc).mean() > 0.9
    proba = model.predict_proba(X)
    assert proba.shape == (len(yc), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    assert list(model.classes_) == [0, 1]
    assert model.n_classes_ == 2


def test_classifier_string_labels(rng):
    X, y = _make_reg(rng)
    yc = np.where(y > np.median(y), "pos", "neg")
    model = LGBMClassifier(n_estimators=10, num_leaves=15,
                           min_child_samples=5)
    model.fit(X, yc)
    pred = model.predict(X)
    assert set(pred) <= {"pos", "neg"}
    assert (pred == yc).mean() > 0.9


def test_multiclass_classifier(rng):
    X, y = _make_reg(rng)
    y3 = np.digitize(y, np.quantile(y, [0.33, 0.66]))
    model = LGBMClassifier(n_estimators=10, num_leaves=15,
                           min_child_samples=5)
    model.fit(X, y3)
    assert model.n_classes_ == 3
    proba = model.predict_proba(X)
    assert proba.shape == (len(y3), 3)
    assert (model.predict(X) == y3).mean() > 0.8


def test_ranker(rng):
    X, y = _make_reg(rng, n=300)
    rel = rng.integers(0, 4, size=300)
    group = np.full(15, 20)
    model = LGBMRanker(n_estimators=8, num_leaves=7, min_child_samples=3)
    model.fit(X, rel, group=group, eval_set=[(X, rel)], eval_group=[group],
              eval_at=[3, 5])
    assert "ndcg@3" in model.evals_result_["valid_0"]
    assert "ndcg@5" in model.evals_result_["valid_0"]
    assert model.predict(X).shape == (300,)


def test_class_weight_original_label_space(rng):
    """class_weight dict keys are user labels, not encoded ones."""
    X, y = _make_reg(rng)
    yc = np.where(y > np.median(y), 5, 9)  # labels {5, 9}, encoded {0, 1}
    m = LGBMClassifier(n_estimators=5, num_leaves=7, min_child_samples=5,
                       class_weight={5: 10.0, 9: 1.0})
    m.fit(X, yc)
    w = m._class_weights_to_sample_weight(yc)
    assert set(np.unique(w)) == {10.0, 1.0}
    assert (w[yc == 5] == 10.0).all() and (w[yc == 9] == 1.0).all()
    assert (m.predict(X) != 0).all()  # predictions in original label space


def test_custom_objective(rng):
    X, y = _make_reg(rng)

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    model = LGBMRegressor(n_estimators=15, num_leaves=15,
                          min_child_samples=5, objective=l2_obj)
    model.fit(X, y)
    pred = model.predict(X)
    assert 1 - np.var(y - pred) / np.var(y) > 0.5


@pytest.mark.slow
def test_sklearn_integration(rng):
    from sklearn.model_selection import GridSearchCV, cross_val_score
    X, y = _make_reg(rng, n=200)
    model = LGBMRegressor(n_estimators=5, num_leaves=7, min_child_samples=5)
    scores = cross_val_score(model, X, y, cv=2)
    assert len(scores) == 2
    # clone/get_params/set_params round trip
    from sklearn.base import clone
    c = clone(model)
    assert c.get_params()["n_estimators"] == 5
    c.set_params(n_estimators=3)
    assert c.get_params()["n_estimators"] == 3


def test_pandas_input(rng):
    pd = pytest.importorskip("pandas")
    X, y = _make_reg(rng, n=200)
    df = pd.DataFrame(X, columns=[f"col_{i}" for i in range(X.shape[1])])
    model = LGBMRegressor(n_estimators=5, num_leaves=7, min_child_samples=5)
    model.fit(df, y)
    assert model.feature_name_ == list(df.columns)
    assert model.predict(df).shape == (200,)


def test_plot_importance_and_metric(rng):
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    X, y = _make_reg(rng, n=200)
    model = LGBMRegressor(n_estimators=10, num_leaves=7, min_child_samples=5)
    model.fit(X, y, eval_set=[(X, y)])
    ax = lgb.plot_importance(model)
    assert len(ax.patches) > 0
    ax2 = lgb.plot_metric(model.evals_result_)
    assert ax2.get_xlabel() == "Iterations"
    ax3 = lgb.plot_split_value_histogram(model, feature=0)
    assert len(ax3.patches) > 0
    import matplotlib.pyplot as plt
    plt.close("all")


def test_decision_function_and_feature_names_in(rng):
    """sklearn conveniences: decision_function == raw margins;
    feature_names_in_ raises for anonymous features, returns names for
    pandas input (ref: sklearn.py:1769, :1368)."""
    import pandas as pd
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(int)
    clf = lgb.LGBMClassifier(n_estimators=5, num_leaves=7,
                             min_child_samples=5, verbose=-1)
    clf.fit(X, y)
    margins = clf.decision_function(X)
    np.testing.assert_allclose(
        margins, clf.predict_proba(X, raw_score=True), rtol=1e-9)
    import pytest as _pytest
    with _pytest.raises(AttributeError):
        _ = clf.feature_names_in_

    df = pd.DataFrame(X, columns=["a", "b", "c", "d"])
    clf2 = lgb.LGBMClassifier(n_estimators=3, num_leaves=7,
                              min_child_samples=5, verbose=-1)
    clf2.fit(df, y)
    assert list(clf2.feature_names_in_) == ["a", "b", "c", "d"]
