"""jaxlint static-analysis pass: rule coverage, suppression, baseline.

One fixture snippet per rule ID (JL001-JL005) asserts each rule fires;
suppression tests cover the three anchor positions (same line, comment
line above, enclosing def line) plus ``disable=all``; baseline tests
assert the known/new split and the CLI exit-code contract that gates CI
(exit 0 on no new findings, nonzero when a seeded violation appears).

Pure stdlib on the analysis side — no jax import; the fixtures are
linted as source strings, never executed (only the two repo-wide gate
tests pay the few-second full-package pass).
"""
import os
import subprocess
import sys
import textwrap

import pytest

from lightgbm_tpu.analysis import jaxlint
from lightgbm_tpu.analysis.jaxlint import (
    default_baseline_path,
    diff_against_baseline,
    lint_source,
    load_baseline,
    run_paths,
    save_baseline,
)
from lightgbm_tpu.analysis.rules import RULE_IDS

REPO_ROOT = os.path.join(os.path.dirname(__file__), "..")

# One violation per rule ID. Linted under a kernel-relative path so JL004
# (kernel files only) participates.
FIXTURE = textwrap.dedent('''\
    import time

    import jax
    import jax.numpy as jnp


    @jax.jit
    def host_sync(x):
        return x.item()                      # <- JL001


    @jax.jit
    def tracer_leak(x):
        y = jnp.abs(x)
        if x > 0:                            # <- JL002
            return y
        return -y


    apply_fn = jax.jit(lambda tree, cfg: tree)


    def recompile_hazard(tree):
        return apply_fn(tree, {"lr": 0.1})   # <- JL003


    @jax.jit
    def widening(x):
        return x + jnp.array(1.5)            # <- JL004


    def unsynced_timing(a, b):
        t0 = time.perf_counter()
        out = jnp.dot(a, b)
        t1 = time.perf_counter()             # <- JL005
        return out, t1 - t0
''')
KERNEL_REL = "lightgbm_tpu/ops/_jaxlint_fixture.py"


def _lint(src, rel=KERNEL_REL):
    return lint_source(src, rel)


# ---------------------------------------------------------------------------
# rule firing
# ---------------------------------------------------------------------------

def test_fixture_flags_every_rule_exactly_once():
    findings = _lint(FIXTURE)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert sorted(by_rule) == sorted(RULE_IDS), (
        f"expected one finding per rule, got: "
        f"{[(f.rule, f.line, f.message) for f in findings]}")
    for rule, fs in by_rule.items():
        assert len(fs) == 1, (rule, [(f.line, f.message) for f in fs])
    scopes = {f.rule: f.scope for f in findings}
    assert scopes["JL001"] == "host_sync"
    assert scopes["JL002"] == "tracer_leak"
    assert scopes["JL003"] == "recompile_hazard"
    assert scopes["JL004"] == "widening"
    assert scopes["JL005"] == "unsynced_timing"


def test_jl004_only_fires_in_kernel_files():
    findings = _lint(FIXTURE, rel="lightgbm_tpu/models/_fixture.py")
    assert "JL004" not in {f.rule for f in findings}
    assert {"JL001", "JL002", "JL003", "JL005"} <= {f.rule
                                                    for f in findings}


def test_jl004_like_ctors_never_flag():
    """*_like constructors inherit dtype from the template array — a
    float fill value cannot promote, so full_like must never flag
    (while jnp.full's fill value DOES decide the dtype and does)."""
    src = textwrap.dedent('''\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def inherits(x):
            return jnp.full_like(x, 1.5)

        @jax.jit
        def hazard(x):
            return x + jnp.full((4,), 1.5)

        @jax.jit
        def full_explicit(x):
            return x + jnp.full((4,), 1.5, jnp.float32)
    ''')
    hits = [f for f in _lint(src) if f.rule == "JL004"]
    assert [f.scope for f in hits] == ["hazard"], hits


def test_static_shape_access_is_not_a_tracer_leak():
    src = textwrap.dedent('''\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def ok(x):
            acc = jnp.zeros_like(x)
            if x.shape[0] > 4:
                acc = acc + 1
            for _ in range(x.ndim):
                acc = acc * 2
            return acc
    ''')
    assert [f for f in _lint(src) if f.rule == "JL002"] == []


def test_syntax_error_reports_jl000():
    findings = _lint("def broken(:\n")
    assert [f.rule for f in findings] == ["JL000"]


# ---------------------------------------------------------------------------
# suppression anchors
# ---------------------------------------------------------------------------

SUPPRESS_VARIANTS = {
    "same_line": '''\
        import jax

        @jax.jit
        def f(x):
            return x.item()  # jaxlint: disable=JL001
    ''',
    "line_above": '''\
        import jax

        @jax.jit
        def f(x):
            # jaxlint: disable=JL001 -- deliberate trace-time probe
            return x.item()
    ''',
    "def_line": '''\
        import jax

        @jax.jit
        def f(x):  # jaxlint: disable=JL001
            return x.item()
    ''',
    "disable_all": '''\
        import jax

        @jax.jit
        def f(x):
            return x.item()  # jaxlint: disable=all
    ''',
    # a plain-word reason after the rule list must not defeat the match
    "word_reason": '''\
        import jax

        @jax.jit
        def f(x):
            return x.item()  # jaxlint: disable=JL001 trace time probe
    ''',
}


@pytest.mark.parametrize("variant", sorted(SUPPRESS_VARIANTS))
def test_suppression_honored(variant):
    src = textwrap.dedent(SUPPRESS_VARIANTS[variant])
    assert _lint(src) == [], variant


def test_suppression_is_rule_specific():
    src = textwrap.dedent('''\
        import jax

        @jax.jit
        def f(x):
            return x.item()  # jaxlint: disable=JL002
    ''')
    assert [f.rule for f in _lint(src)] == ["JL001"]


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------

def test_baseline_splits_known_from_new(tmp_path):
    findings = _lint(FIXTURE)
    bl = tmp_path / "jaxlint_baseline.json"
    save_baseline(str(bl), findings)
    new, known = diff_against_baseline(findings, load_baseline(str(bl)))
    assert new == [] and len(known) == len(RULE_IDS)

    # a freshly introduced violation is NEW; the baselined ones stay known
    seeded = FIXTURE + textwrap.dedent('''\


        @jax.jit
        def fresh(x):
            return x.tolist()
    ''')
    new, known = diff_against_baseline(_lint(seeded),
                                       load_baseline(str(bl)))
    assert len(known) == len(RULE_IDS)
    assert [f.rule for f in new] == ["JL001"]
    assert new[0].scope == "fresh"


def test_fingerprint_stable_when_duplicate_line_is_suppressed():
    """Suppressing the first of two identical flagged lines must not
    re-key the survivor's occurrence counter (else the baseline entry for
    an untouched line goes spuriously 'new')."""
    dup = textwrap.dedent('''\
        import jax

        @jax.jit
        def f(x, out):
            out.append(x.item())
            out.append(x.item())
            return out
    ''')
    both = _lint(dup)
    assert [f.occ for f in both] == [0, 1]
    suppressed_first = dup.replace(
        "    out.append(x.item())",
        "    # jaxlint: disable=JL001\n    out.append(x.item())", 1)
    survivor, = _lint(suppressed_first)
    assert survivor.occ == 1
    assert survivor.fingerprint == both[1].fingerprint


def test_baseline_fingerprint_survives_line_drift():
    shifted = "# a new comment line\n\n" + FIXTURE
    orig = {f.fingerprint for f in _lint(FIXTURE)}
    assert {f.fingerprint for f in _lint(shifted)} == orig


# ---------------------------------------------------------------------------
# CLI contract (what scripts/jaxlint.py and scripts/check.sh gate on)
# ---------------------------------------------------------------------------

def test_cli_exit_codes_roundtrip(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text(textwrap.dedent('''\
        import jax

        @jax.jit
        def f(x):
            return x.item()
    '''))
    argv = [str(target)]
    assert jaxlint.main(argv, root=str(tmp_path)) == 1      # new finding
    assert jaxlint.main(argv + ["--update-baseline"],
                        root=str(tmp_path)) == 0            # accept
    assert jaxlint.main(argv, root=str(tmp_path)) == 0      # now known
    out = capsys.readouterr().out
    assert "1 known" in out

    target.write_text(target.read_text() + textwrap.dedent('''\


        @jax.jit
        def g(x):
            return x.tolist()
    '''))
    assert jaxlint.main(argv, root=str(tmp_path)) == 1      # seeded -> gate


def test_update_baseline_refuses_syntax_errors(tmp_path, capsys):
    """--update-baseline must not report success over an unparsable tree:
    JL000 findings are never baselined, so accepting would leave the very
    next plain run red on an untouched tree."""
    (tmp_path / "ok.py").write_text(textwrap.dedent('''\
        import jax

        @jax.jit
        def f(x):
            return x.item()
    '''))
    (tmp_path / "broken.py").write_text("def broken(:\n")
    argv = [str(tmp_path), "--update-baseline"]
    assert jaxlint.main(argv, root=str(tmp_path)) == 1
    out = capsys.readouterr().out
    assert "JL000" in out and "refusing" in out
    assert not (tmp_path / "jaxlint_baseline.json").exists()


def test_partial_update_baseline_keeps_unscanned_files(tmp_path, capsys):
    """`--update-baseline some/path` must only replace the scanned
    files' entries — accepted findings elsewhere survive (a partial
    update must never turn the gate red on untouched files)."""
    (tmp_path / "a.py").write_text(textwrap.dedent('''\
        import jax

        @jax.jit
        def fa(x):
            return x.item()
    '''))
    (tmp_path / "b.py").write_text(textwrap.dedent('''\
        import jax

        @jax.jit
        def fb(x):
            return x.tolist()
    '''))
    root = str(tmp_path)
    assert jaxlint.main([root, "--update-baseline"], root=root) == 0
    # partial update over b.py only: a.py's accepted finding must survive
    assert jaxlint.main([str(tmp_path / "b.py"), "--update-baseline"],
                        root=root) == 0
    capsys.readouterr()
    assert jaxlint.main([root], root=root) == 0, (
        "partial --update-baseline wiped entries for unscanned files:\n"
        + capsys.readouterr().out)
    out = capsys.readouterr().out
    assert "2 known" in out


def test_repo_is_clean_against_checked_in_baseline(capsys):
    """Acceptance gate: `python scripts/jaxlint.py` exits 0 on the repo."""
    assert os.path.exists(default_baseline_path(REPO_ROOT)), (
        "jaxlint_baseline.json missing — regenerate with "
        "`python scripts/jaxlint.py --update-baseline`")
    rc = jaxlint.main([], root=REPO_ROOT)
    out = capsys.readouterr().out
    assert rc == 0, f"new jaxlint findings in the repo:\n{out}"


def test_repo_seeded_violation_gates(tmp_path):
    """Acceptance gate: a seeded JL001-JL005 violation exits nonzero."""
    seeded = tmp_path / "seeded.py"
    seeded.write_text(textwrap.dedent('''\
        import jax

        @jax.jit
        def seeded_violation(x):
            return x.item()
    '''))
    rc = jaxlint.main([str(seeded)], root=REPO_ROOT)
    assert rc == 1


def test_hof_operand_args_are_not_factories():
    """Only the CALLABLE positions of a lax higher-order op mark
    factories/traced callees. A helper whose RESULT feeds an operand
    slot (`init = helper(x); lax.while_loop(cond, body, init)`) must
    stay in jit scope — conflating the two exempted real host-sync
    hazards from the gate."""
    src = textwrap.dedent('''\
        import jax
        from jax import lax

        def helper(x):
            return x.item()

        def cond_fn(c):
            return c[0] < 3

        def body_fn(c):
            return (c[0] + 1, c[1])

        @jax.jit
        def grow(x):
            init = helper(x)
            return lax.while_loop(cond_fn, body_fn, (0, init))
    ''')
    hits = [f for f in _lint(src) if f.rule == "JL001"]
    assert [f.scope for f in hits] == ["helper"], hits


def test_cli_wrapper_never_imports_jax_or_the_package():
    """The gate must run on jax-free images and never touch a wedged
    accelerator tunnel: loading scripts/jaxlint.py may not pull in jax
    or lightgbm_tpu's package root (whose __init__ imports jax)."""
    script = os.path.abspath(
        os.path.join(REPO_ROOT, "scripts", "jaxlint.py"))
    probe = textwrap.dedent(f'''
        import runpy, sys
        before = set(sys.modules)
        runpy.run_path({script!r}, run_name="loaded_for_test")
        new = set(sys.modules) - before
        bad = [m for m in new
               if m == "jax" or m.startswith(("jax.", "jaxlib"))
               or m == "lightgbm_tpu" or m.startswith("lightgbm_tpu.")]
        assert not bad, f"CLI imported {{sorted(bad)}}"
        print("CLEAN")
    ''')
    out = subprocess.run([sys.executable, "-c", probe],
                         capture_output=True, text=True)
    assert "CLEAN" in out.stdout, out.stderr


def test_run_paths_resolves_cross_module_jit_scope(tmp_path):
    """A function called by bare name from another module's jitted body
    enters jit scope (how ops/split.py is reached from core/grower.py)."""
    (tmp_path / "kernels.py").write_text(textwrap.dedent('''\
        def scan_feature(h):
            return h.item()
    '''))
    (tmp_path / "driver.py").write_text(textwrap.dedent('''\
        import jax
        from kernels import scan_feature

        @jax.jit
        def grow(h):
            return scan_feature(h)
    '''))
    findings = run_paths([str(tmp_path)], str(tmp_path))
    hits = [f for f in findings if f.rule == "JL001"]
    assert any(f.path == "kernels.py" and f.scope == "scan_feature"
               for f in hits), findings
