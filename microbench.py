"""Primitive-op microbenchmarks on the current backend.

Measures the building blocks the grower's schedule is made of, so kernel
choices (einsum dtype, partition primitive, block size) are driven by
device numbers instead of guesses. Run on the real chip:

    python microbench.py            # all suites
    python microbench.py hist part  # chosen suites
"""
import sys
import time

import numpy as np


def _sync(out):
    """Real device barrier: fetch a scalar from the last output.

    `jax.block_until_ready` is a no-op through the axon tunnel (async
    dispatch); a host transfer is the only honest barrier. Single-chip
    programs run in dispatch order, so syncing the last output syncs all.
    """
    import jax
    import jax.numpy as jnp
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    # null-sync baseline: the one tunnel round-trip inside the timed loop
    # (~70 ms) would otherwise bias per-iter times by round_trip/iters
    t0 = time.perf_counter()
    _sync(out)
    rt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return max((time.perf_counter() - t0) - rt, 1e-9) / iters


def bench_hist():
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import hist_rowmajor, hist_xla

    rng = np.random.default_rng(0)
    R, F, B = 1_048_576, 28, 256
    bins_rm = jnp.asarray(rng.integers(0, B - 1, (R, F), dtype=np.uint8))
    gh = jnp.asarray(rng.normal(size=(R, 3)).astype(np.float32))
    ghq = jnp.asarray(rng.integers(-8, 8, (R, 3), dtype=np.int8))
    for S in (16384, 131072, 1_048_576):
        for blk in (4096, 8192, 16384):
            for name, g, dt in (("f32", gh, "float32"),
                                ("bf16", gh, "bfloat16"),
                                ("int8", ghq, "float32")):
                f = jax.jit(lambda b, g, dt=dt, blk=blk: hist_rowmajor(
                    b, g, num_bin=B, block_rows=blk, dtype=dt))
                dt_s = timeit(f, bins_rm[:S], g[:S])
                gbps = S * F * (B * (4 if name == "f32" else
                                     2 if name == "bf16" else 1)) / dt_s / 1e9
                print(f"hist_rm S={S:8d} blk={blk:6d} {name}: "
                      f"{dt_s*1e3:8.3f} ms  ({S/dt_s/1e9:.2f} Grows/s, "
                      f"onehot {gbps:.0f} GB/s)", flush=True)
    f = jax.jit(lambda b, g: hist_xla(b, g, num_bin=B, block_rows=8192))
    dt_s = timeit(f, bins_rm.T.copy(), gh)
    print(f"hist_xla(F-major) R={R}: {dt_s*1e3:8.3f} ms", flush=True)


def bench_pallas_rm():
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.hist_pallas import hist_pallas_rm

    rng = np.random.default_rng(0)
    R, F, B = 1_048_576, 28, 256
    bins_rm = jnp.asarray(rng.integers(0, B - 1, (R, F), dtype=np.uint8))
    gh = jnp.asarray(rng.normal(size=(R, 3)).astype(np.float32))
    ghq = jnp.asarray(rng.integers(-8, 8, (R, 3), dtype=np.int8))
    ghb = gh.astype(jnp.bfloat16)
    for S in (131072, 1_048_576):
        for blk in (256, 512, 1024):
            for ft in (8, 16, 32):
                for name, g in (("f32", gh), ("bf16", ghb), ("int8", ghq)):
                    try:
                        f = jax.jit(
                            lambda b, g, blk=blk, ft=ft: hist_pallas_rm(
                                b, g, num_bin=B, block_rows=blk,
                                feature_tile=ft))
                        dt_s = timeit(f, bins_rm[:S], g[:S])
                        print(f"hist_pallas_rm S={S:8d} blk={blk:5d} "
                              f"ft={ft:2d} {name}: {dt_s*1e3:8.3f} ms "
                              f"({S/dt_s/1e9:.2f} Grows/s)", flush=True)
                    except Exception as e:
                        print(f"hist_pallas_rm S={S} blk={blk} ft={ft} "
                              f"{name}: FAIL {type(e).__name__}: "
                              f"{str(e)[:100]}", flush=True)


def bench_pallas():
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.hist_pallas import hist_pallas

    rng = np.random.default_rng(0)
    R, F, B = 1_048_576, 28, 256
    bins_t = jnp.asarray(rng.integers(0, B - 1, (F, R), dtype=np.uint8))
    gh = jnp.asarray(rng.normal(size=(R, 3)).astype(np.float32))
    for S in (16384, 131072, 1_048_576):
        for blk in (1024, 2048, 4096):
            for ft in (4, 7, 14, 28):
                try:
                    f = jax.jit(lambda b, g, blk=blk, ft=ft: hist_pallas(
                        b, g, num_bin=B, block_rows=blk, feature_tile=ft))
                    dt_s = timeit(f, bins_t[:, :S], gh[:S])
                    print(f"hist_pallas S={S:8d} blk={blk:5d} ft={ft:2d}: "
                          f"{dt_s*1e3:8.3f} ms  ({S/dt_s/1e9:.2f} Grows/s)",
                          flush=True)
                except Exception as e:
                    print(f"hist_pallas S={S} blk={blk} ft={ft}: FAIL "
                          f"{type(e).__name__}: {str(e)[:120]}", flush=True)


def bench_hist_level():
    """Level-mode per-node histogram A/B (ISSUE 6): the one-launch
    sorted-segment Pallas kernel (pallas_level) vs the blocks
    composition (interior blocks + 2x edge windows, einsum inner) vs
    the per-feature scatter, at level shapes — depth 4/7/10,
    F in {28, 200}, B=255, quantized on/off. INFORMATIONAL: this raw
    kernel table goes to the runbook/logs; the TUNED.json
    ``level_hist_backend`` decision is made by tpu_session_auto stage
    4.7 from END-TO-END bench arms (``ab_level_kernel_*``), not from
    this table — a kernel that wins here but loses in the training
    loop (layout/fusion effects) must not become the default.

    On CPU the matrix shrinks (32k rows, depth<=7, F=28, no einsum at
    F=200) and the Pallas arm runs the INTERPRETER — mechanics proof
    only, never a tuning signal; set MB_LEVEL_PALLAS=0/1 to force the
    arm off/on.
    """
    import os
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.core.level_grower import (hist_level_blocks,
                                                hist_level_scatter)
    from lightgbm_tpu.ops.hist_level_pallas import hist_level, level_tiles

    rng = np.random.default_rng(0)
    B = 255
    on_tpu = jax.default_backend() == "tpu"
    R = 1_048_576 if on_tpu else 32_768
    feats = (28, 200) if on_tpu else (28,)
    depths = (4, 7, 10) if on_tpu else (4, 7)
    run_pallas = os.environ.get("MB_LEVEL_PALLAS",
                                "1" if on_tpu else "0") == "1"
    for F in feats:
        bins = jnp.asarray(rng.integers(0, B, (R, F), dtype=np.uint8))
        gh = jnp.asarray(rng.normal(size=(R, 3)).astype(np.float32))
        ghq = jnp.asarray(rng.integers(-8, 8, (R, 3), dtype=np.int8))
        for depth in depths:
            n_d = 1 << depth
            if F * n_d * B * 3 * 4 > 300 << 20:
                # [n_d, F, B, 3] output past ~300 MB: not a live shape
                # (the level phase's memory gate rejects it upstream)
                print(f"hist_level F={F} d={depth}: SKIP (output "
                      f"{F * n_d * B * 3 * 4 >> 20} MB)", flush=True)
                continue
            local = jnp.asarray(rng.integers(0, n_d, R).astype(np.int32))
            in_lvl = jnp.ones(R, bool)
            for qname, g, acc in (("f32", gh, jnp.float32),
                                  ("int8", ghq, jnp.int32)):
                # one jit per measured arm is the POINT here: each
                # (shape, backend) pair is timed as its own program,
                # warmed by timeit before the timed loop
                arms = [
                    # jaxlint: disable=JL003 — per-arm jit, warmed by timeit
                    ("scatter", jax.jit(
                        lambda bt, gg, n_d=n_d, acc=acc:
                        hist_level_scatter(bt, gg, local, in_lvl, n_d,
                                           num_bin=B, acc_dtype=acc)),
                     bins.T, g),
                    # jaxlint: disable=JL003 — per-arm jit, warmed by timeit
                    ("blocks", jax.jit(
                        lambda bb, gg, n_d=n_d, F=F, acc=acc:
                        hist_level_blocks(
                            bb, gg, local, in_lvl, n_d, R, F,
                            num_bin=B, input_dtype="float32",
                            rm_backend="einsum", acc_dtype=acc)),
                     bins, g),
                ]
                if run_pallas:
                    ft, br, ok = level_tiles(8, B, 512, n_d, R)
                    if ok:
                        # jaxlint: disable=JL003 — per-arm jit, warmed by timeit
                        arms.append(("pallas_level", jax.jit(
                            lambda bb, gg, n_d=n_d, br=br, ft=ft:
                            hist_level(bb, gg, local, in_lvl, n_d, B,
                                       block_rows=br, feature_tile=ft)),
                            bins, g))
                    else:
                        print(f"hist_level F={F} d={depth} {qname} "
                              f"pallas_level: SKIP (tiles infeasible)",
                              flush=True)
                for name, f, b_arg, g_arg in arms:
                    try:
                        dt_s = timeit(f, b_arg, g_arg, iters=5,
                                      warmup=2)
                        print(f"hist_level F={F:3d} d={depth:2d} "
                              f"{qname}: {name:12s} {dt_s*1e3:9.3f} ms "
                              f"({R/dt_s/1e9:.2f} Grows/s)", flush=True)
                    except Exception as e:
                        print(f"hist_level F={F} d={depth} {qname} "
                              f"{name}: FAIL {type(e).__name__}: "
                              f"{str(e)[:100]}", flush=True)


def bench_part():
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(0)
    R = 1_048_576
    seg = jnp.asarray(rng.permutation(R).astype(np.int32))
    go_left = jnp.asarray(rng.integers(0, 2, R).astype(bool))
    vals = jnp.asarray(rng.normal(size=(R,)).astype(np.float32))

    def part_scatter(seg, lm):
        pos = jnp.arange(R, dtype=jnp.int32)
        dst_l = jnp.cumsum(lm.astype(jnp.int32)) - 1
        nL = dst_l[-1] + 1
        dst_r = nL + jnp.cumsum((~lm).astype(jnp.int32)) - 1
        dest = jnp.where(lm, dst_l, dst_r)
        return jnp.zeros_like(seg).at[dest].set(seg, unique_indices=True)

    def part_sort(seg, lm):
        key = (~lm).astype(jnp.int32)
        _, out = lax.sort((key, seg), num_keys=1, is_stable=True)
        return out

    for name, f in (("scatter", part_scatter), ("sort", part_sort)):
        dt_s = timeit(jax.jit(f), seg, go_left)
        print(f"partition/{name} R={R}: {dt_s*1e3:8.3f} ms", flush=True)

    # small-bucket fixed costs decide the partition_mode=auto threshold
    # (the compact scheduler's lax.switch buckets go down to min_bucket)
    for n in (2048, 8192, 32768, 131072):
        segn = seg[:n]
        lmn = go_left[:n]

        def part_scatter_n(seg, lm, n=n):
            dst_l = jnp.cumsum(lm.astype(jnp.int32)) - 1
            nL = dst_l[-1] + 1
            dst_r = nL + jnp.cumsum((~lm).astype(jnp.int32)) - 1
            dest = jnp.where(lm, dst_l, dst_r)
            return jnp.zeros_like(seg).at[dest].set(
                seg, unique_indices=True)

        def part_sort_n(seg, lm):
            key = (~lm).astype(jnp.int32)
            _, out = lax.sort((key, seg), num_keys=1, is_stable=True)
            return out

        for name, f in (("scatter", part_scatter_n), ("sort", part_sort_n)):
            dt_s = timeit(jax.jit(f), segn, lmn)
            print(f"partition/{name} n={n}: {dt_s*1e3:8.3f} ms", flush=True)

    def gather_rows(seg, v):
        return jnp.take(v, seg, axis=0)

    dt_s = timeit(jax.jit(gather_rows), seg, vals)
    print(f"gather f32[R] R={R}: {dt_s*1e3:8.3f} ms", flush=True)

    bins_rm = jnp.asarray(rng.integers(0, 255, (R, 28), dtype=np.uint8))
    dt_s = timeit(jax.jit(lambda s, b: jnp.take(b, s, axis=0)), seg, bins_rm)
    print(f"gather u8[R,28] R={R}: {dt_s*1e3:8.3f} ms", flush=True)

    dt_s = timeit(jax.jit(lambda s, b: b.reshape(-1)[s * 28 + 3]),
                  seg, bins_rm)
    print(f"gather-flat u8 col R={R}: {dt_s*1e3:8.3f} ms", flush=True)

    # packed-row gather candidates: if gather cost is per-ELEMENT, packing
    # 4 u8 bins per i32 word should cut the compact scheduler's per-leaf
    # row gather ~4x (28 u8 -> 7 i32 words per row)
    packed = jnp.asarray(
        np.ascontiguousarray(
            rng.integers(0, 255, (R, 28), dtype=np.uint8)
            .reshape(R, 7, 4)).view(np.uint32).reshape(R, 7))
    dt_s = timeit(jax.jit(lambda s, p: jnp.take(p, s, axis=0)), seg, packed)
    print(f"gather u32packed[R,7] R={R}: {dt_s*1e3:8.3f} ms", flush=True)

    def gather_unpack(s, p):
        w = jnp.take(p, s, axis=0)                       # [R, 7] u32
        parts = [(w >> (8 * k)) & jnp.uint32(0xFF) for k in range(4)]
        return jnp.stack(parts, axis=2).reshape(R, 28).astype(jnp.uint8)

    dt_s = timeit(jax.jit(gather_unpack), seg, packed)
    print(f"gather+unpack u32->u8[R,28] R={R}: {dt_s*1e3:8.3f} ms",
          flush=True)

    bins32 = bins_rm.astype(jnp.int32)
    dt_s = timeit(jax.jit(lambda s, b: jnp.take(b, s, axis=0)), seg, bins32)
    print(f"gather i32[R,28] R={R}: {dt_s*1e3:8.3f} ms", flush=True)


def bench_fullpass():
    """One masked full-row pass (the round-1 design's per-split cost)."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import hist_xla

    rng = np.random.default_rng(0)
    R, F, B = 1_048_576, 28, 256
    bins_t = jnp.asarray(rng.integers(0, B - 1, (F, R), dtype=np.uint8))
    gh = jnp.asarray(rng.normal(size=(R, 3)).astype(np.float32))
    leaf = jnp.asarray(rng.integers(0, 255, R).astype(np.int32))

    def masked(b, g, lid):
        m = (lid == 3).astype(g.dtype)
        return hist_xla(b, g * m[:, None], num_bin=B, block_rows=8192)

    dt_s = timeit(jax.jit(masked), bins_t, gh, leaf)
    print(f"masked full pass R={R}: {dt_s*1e3:8.3f} ms", flush=True)


def bench_multival():
    """Sparse [R, K] histogram strategies: scatter-add vs sort+segment
    (drives the multival kernel choice on device — ref role:
    multi_val_bin_wrapper.cpp picking dense/sparse row-wise bins)."""
    import jax
    import jax.numpy as jnp

    R, K, F, B = 200_000, 32, 1000, 64
    rng = np.random.default_rng(0)
    idx = rng.integers(0, F, size=(R, K)).astype(np.int32)
    idx[rng.uniform(size=(R, K)) < 0.2] = -1          # padding
    binv = rng.integers(0, B, size=(R, K)).astype(np.int32)
    gh = rng.normal(size=(R, 3)).astype(np.float32)
    idx_d, binv_d, gh_d = map(jnp.asarray, (idx, binv, gh))

    def scatter(i, b, g):
        valid = i >= 0
        flat = jnp.where(valid, i * B + b, F * B)
        out = jnp.zeros((F * B + 1, 3), jnp.float32)
        return out.at[flat].add(g[:, None, :])[:-1].reshape(F, B, 3)

    def sort_seg(i, b, g):
        valid = (i >= 0).reshape(-1)
        flat = jnp.where(valid, (i * B + b).reshape(-1), F * B)
        gr = jnp.repeat(g, K, axis=0) * valid[:, None]
        order = jnp.argsort(flat)
        return jax.ops.segment_sum(
            gr[order], flat[order], num_segments=F * B + 1,
            indices_are_sorted=True)[:-1].reshape(F, B, 3)

    for name, fn in (("scatter", scatter), ("sort+segsum", sort_seg)):
        dt = timeit(jax.jit(fn), idx_d, binv_d, gh_d)
        print(f"multival {name} R={R} K={K} F={F} B={B}: "
              f"{dt*1e3:8.3f} ms", flush=True)


def bench_comms():
    """Histogram-collective A/B (ISSUE 12): the per-split reduce+scan
    unit under shard_map — allreduce (psum the full [F, B, 3] hist,
    replicated scan) vs reduce_scatter (psum_scatter to a feature
    window, window scan, packed-record combine). Prints the ring-model
    bytes-on-the-wire next to each timing so device numbers can be read
    against the 2(N-1)/N·|H| -> (N-1)/N·|H| claim. Needs >= 2 devices
    (on CPU run under XLA_FLAGS=--xla_force_host_platform_device_count=2
    — the __main__ hook sets it when the suite is selected first)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.ops.split import (FeatureMeta, SplitHyperParams,
                                        best_split_for_leaf)
    from lightgbm_tpu.parallel import build_mesh
    from lightgbm_tpu.parallel.data_parallel import (
        _make_sharded, make_feature_window, make_global_best_combine)

    n_dev = len(jax.devices())
    if n_dev < 2:
        print("comms: SKIP (needs >= 2 devices; on CPU set XLA_FLAGS="
              "--xla_force_host_platform_device_count=2)", flush=True)
        return
    mesh = build_mesh(n_dev)
    hp = SplitHyperParams(min_data_in_leaf=20)
    B = 255
    rng = np.random.default_rng(0)
    for F in (28, 200):
        meta = FeatureMeta(
            num_bin=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            default_bin=jnp.zeros(F, jnp.int32),
            is_categorical=jnp.zeros(F, bool))
        h = (rng.integers(0, 64, (n_dev, F, B, 3)) * 0.25).astype(
            np.float32)
        sg = float(h[..., 0].sum())
        sh_ = float(h[..., 1].sum()) + 1.0
        cn = float(h[..., 2].sum()) + 1.0
        reduce_rs, scan_window = make_feature_window(meta, n_dev, "data")
        combine = make_global_best_combine("data")
        fm = jnp.ones(F, bool)

        def ar_unit(hl):
            hg = lax.psum(hl[0], "data")
            rec = best_split_for_leaf(hg, sg, sh_, cn, 0.0, meta, hp, fm)
            return rec.gain, rec.feature

        def rs_unit(hl):
            hw = reduce_rs(hl[0])
            hw, meta_w, fids, fm_w, gp, ru = scan_window(
                hw, None, fm, None, None)
            rec = best_split_for_leaf(hw, sg, sh_, cn, 0.0, meta_w, hp,
                                      fm_w, feature_ids=fids)
            rec = combine(rec)
            return rec.gain, rec.feature

        spec = P("data", None, None, None)
        hist_mb = F * B * 3 * 4 / 2 ** 20
        for name, fn, factor in (("allreduce", ar_unit,
                                  2 * (n_dev - 1) / n_dev),
                                 ("reduce_scatter", rs_unit,
                                  (n_dev - 1) / n_dev)):
            # jaxlint: disable=JL003 — one DISTINCT program per arm
            # (allreduce vs reduce_scatter), each jitted exactly once
            unit = jax.jit(_make_sharded(fn, mesh, in_specs=(spec,),
                                         out_specs=(P(), P())))
            dt = timeit(unit, jnp.asarray(h))
            print(f"comms {name:14s} F={F:3d} B={B}: {dt*1e3:8.3f} ms  "
                  f"(wire ~{hist_mb*factor:6.2f} MB/reduce of "
                  f"{hist_mb:.2f} MB hist, {n_dev} dev)", flush=True)


SUITES = {"hist": bench_hist, "pallas": bench_pallas,
          "pallas_rm": bench_pallas_rm, "hist_level": bench_hist_level,
          "part": bench_part, "fullpass": bench_fullpass,
          "multival": bench_multival, "comms": bench_comms}

if __name__ == "__main__":
    picks = sys.argv[1:] or list(SUITES)
    if "comms" in picks and "jax" not in sys.modules:
        # the comms suite needs a mesh: on a 1-device CPU box expose 2
        # virtual devices BEFORE the backend initializes (no-op when
        # the flag — or a real multi-device platform — is already set)
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags and \
                os.environ.get("JAX_PLATFORMS", "") == "cpu":
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
    import jax
    print(f"backend={jax.default_backend()} devices={jax.devices()}",
          flush=True)
    for p in picks:
        print(f"== {p} ==", flush=True)
        SUITES[p]()
